"""Continuous-batching decode engine over the paged KV cache.

The serving-side half of the paged decode subsystem (the kernel half is
`ops/pallas/paged_attention.py`, the model half `models/gpt.py`
PagedKVCache): a fixed-slot decode batch that admits and evicts
sequences MID-FLIGHT, recycling completed sequences' KV pages to newly
admitted ones. This is what the paging buys beyond ragged bandwidth —
the dense StaticKVCache path must run every co-batched request for the
longest request's duration (or re-prefill), while here a finished slot
is refilled on the next step without touching the other slots' compiled
program.

Design (TPU-native fixed shapes; paper basis: *Ragged Paged Attention*,
PAPERS.md — the same pool/page-table layout its kernel consumes):

- DEVICE state is fully static-shaped: per-layer page pools, one
  ``page_table [num_slots, max_pages]``, ``seq_lens [num_slots]``, and
  the per-slot current token. ONE compiled decode step serves the
  engine's whole lifetime; prefill compiles once per prompt bucket.
- HOST state is the scheduler: a free-list `PageAllocator`, the wait
  queue, and per-slot request bookkeeping. Admission allocates
  ceil(capacity/page) pages and runs a bucket-padded prefill whose
  right padding is redirected to the pool's reserved scratch page
  (models/gpt.py paged_kv_append valid_len), so padded prompts never
  touch real pages; eviction returns the pages to the free list and
  parks the slot on the scratch page at length 0 (an empty slot
  attends nothing and produces defined zeros — see
  paged_attention_reference), so a freed page can be handed to the
  next request without any cross-slot read hazard.
- Inactive slots still ride through the fixed-shape decode step (their
  writes land on the scratch page and their lengths are reset on the
  host); that is the fixed-slot contract that keeps the hot loop at
  one compiled program.

Serving hooks (the `paddle_tpu/serving/` subsystem rides on these;
each defaults OFF so the bare engine behaves exactly as before):

- ``scheduler``: admission-order policy object (duck-typed
  ``select(queue, fits, now)`` / ``shed(queue, now)``) replacing the
  built-in blocking FIFO — serving/scheduler.py's SLO-aware policy.
- ``prefix_cache``: refcounted full-page sharing across requests
  (serving/prefix_cache.py). Admission reuses cached prefix pages and
  prefills only the suffix (models/gpt.py ``prefill_chained``);
  completed prompts' full pages transfer ownership into the cache.
- ``prefill_retry``: a resilience.RetryPolicy retrying transient
  prefill failures at the ``serving.prefill`` fault site.
- per-request ``RequestStats`` (admit/prefill/first-token/finish
  timestamps) surfaced through ``on_token`` / ``on_complete``
  callbacks — the records serving/metrics.py aggregates.

Request lifecycle: queued → prefill → decoding → done, with the
off-ramps evicted (close()), shed (scheduler overload) and failed
(prefill attempts exhausted). Chunked-prefill engines
(``prefill_chunk_tokens``) replace the prefill stage with
prefill_partial: admission binds pages without prefilling, and each
step() advances at most one half-prefilled slot by one page-aligned
chunk through the chained-prefill jit BEFORE the decode step — so
in-flight decode streams keep ticking while a long prompt trickles in
(the TTFT-vs-TPOT head-of-line fix; greedy outputs stay bit-identical
to whole prefill).

Multi-step engines (``multi_step=N``, r19) replace the per-token
launch/readback cadence with one on-device N-step program per
boundary (models/gpt.py ``multi_step_decode``): admission and chunked
prefill run AT the boundary (they mutate the launch's inputs and
donate the pools, so they cannot run under an in-flight launch),
while token delivery/tracing/metrics and the serving loop's inbox
work OVERLAP the launch (dispatch-then-drain: ring K−1 streams after
launch K is dispatched). Greedy outputs stay bit-identical to
``multi_step=1`` (the default, which is byte-for-byte the per-token
engine).

Reference analog: the inference engine's multi-stream serving loop
(`inference/api/analysis_predictor.cc` + TensorRT's enqueue batching),
rebuilt as a scheduler over one jitted step instead of a stream pool.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import (Any, Callable, Dict, Hashable, List, Optional,
                    Sequence, Tuple)

import numpy as np

__all__ = ["PageAllocator", "DecodeRequest", "RequestStats",
           "ContinuousBatchingEngine", "create_decode_engine",
           "SwapFailed"]


class SwapFailed(RuntimeError):
    """A weight hot-swap was refused or could not be applied (r24).

    Raised BEFORE any live state is touched: a torn/corrupt/mismatched
    checkpoint, or an engine that is not at a swappable boundary,
    leaves the old weights serving and the old generation pinned —
    never a half-applied state dict, never mixed tensors."""


class PageAllocator:
    """Host-side free-list allocator over the shared page pool.

    Pages are plain ints in [0, num_pages); the pool's reserved scratch
    page (index num_pages in the device arrays) is never handed out.
    `alloc` is all-or-nothing so a request that does not fit leaves the
    free list untouched (no partial reservations to unwind). Owners are
    arbitrary hashables: requests own by req_id (int), the prefix cache
    owns by ("prefix", key) tuples.

    Reservations (the speculative-decoding discipline): ``reserve``
    claims CAPACITY without binding physical pages; ``alloc_reserved``
    later converts reservation into pages (guaranteed to succeed), and
    ``release_pages(..., rereserve=True)`` converts pages back into
    reservation. ``free_count`` excludes reserved capacity, so
    admission-fit checks and the prefix cache's eviction pressure see
    only genuinely available pages. This is what lets a speculative
    slot grow its page set token-by-token and RETURN wholly-unused
    pages on rejection rollback while its future growth stays
    deadlock-free (capacity was committed at admission).

    ``ledger`` (r18, inference/page_ledger.py): an optional PageLedger
    that every successful mutation appends to — the memory-forensics
    plane. With a ledger attached, ``check_no_leak`` failures dump the
    dangling pages' ownership history instead of bare counts. None
    (the default for direct construction) is byte-for-byte the
    pre-r18 allocator."""

    def __init__(self, num_pages: int, ledger=None):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages))
        self._owned: Dict[Hashable, List[int]] = {}
        self._reserved: Dict[Hashable, int] = {}
        self.ledger = ledger

    @property
    def free_count(self) -> int:
        return len(self._free) - self.reserved_total

    @property
    def reserved_total(self) -> int:
        return sum(self._reserved.values())

    def alloc(self, owner: Hashable, n: int) -> Optional[List[int]]:
        from ..distributed.fault_inject import fault_point
        # chaos site: a transient allocation failure (the host-side
        # analog of an HBM allocator hiccup). Admission treats it like
        # a no-fit and requeues — never a leak, never a wedge.
        fault_point("alloc.page")
        if n > self.free_count:
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        if self.ledger is not None:
            self.ledger.record("alloc", owner, pages)
        return pages

    def reserve(self, owner: Hashable, n: int) -> bool:
        """All-or-nothing capacity claim (no physical pages bound)."""
        from ..distributed.fault_inject import fault_point
        fault_point("alloc.page")  # same chaos regime as alloc()
        if n > self.free_count:
            return False
        if n:
            self._reserved[owner] = self._reserved.get(owner, 0) + n
            if self.ledger is not None:
                self.ledger.record("reserve", owner, n=n)
        return True

    def reserved(self, owner: Hashable) -> int:
        return self._reserved.get(owner, 0)

    def alloc_reserved(self, owner: Hashable, n: int) -> List[int]:
        """Convert ``n`` pages of ``owner``'s reservation into physical
        pages. Never fails: reserve() bounded the claim against the
        free list, and only alloc/alloc_reserved consume it."""
        held = self._reserved.get(owner, 0)
        if n > held:
            raise RuntimeError(
                f"{owner!r} asked for {n} reserved pages but holds a "
                f"reservation of {held}")
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        if held == n:
            self._reserved.pop(owner, None)
        else:
            self._reserved[owner] = held - n
        if self.ledger is not None and pages:
            self.ledger.record("alloc_reserved", owner, pages)
        return pages

    def release_pages(self, owner: Hashable, pages: Sequence[int],
                      rereserve: bool = False) -> None:
        """Return SPECIFIC pages to the free list (rollback of rejected
        speculation). ``rereserve`` converts them back into reservation
        so the owner's growth guarantee is preserved."""
        held = self._owned.get(owner, [])
        for p in pages:
            if p not in held:
                raise RuntimeError(
                    f"release of page {p} not owned by {owner!r}")
            held.remove(p)
            self._free.append(p)
        if not held:
            self._owned.pop(owner, None)
        if rereserve and pages:
            self._reserved[owner] = (self._reserved.get(owner, 0) +
                                     len(pages))
        if self.ledger is not None and pages:
            self.ledger.record("release", owner, pages,
                               rereserve=rereserve)

    def free(self, owner: Hashable) -> int:
        pages = self._owned.pop(owner, [])
        for p in pages:
            if p in self._free:  # double free = scheduler bug
                raise RuntimeError(f"page {p} double-freed")
        self._free.extend(pages)
        res_held = self._reserved.pop(owner, None) or 0
        if self.ledger is not None and (pages or res_held):
            self.ledger.record("free", owner, pages,
                               reserved_freed=res_held)
        return len(pages)

    def transfer(self, owner: Hashable, new_owner: Hashable,
                 pages: Sequence[int]) -> None:
        """Move specific pages between owners (no free-list round trip:
        the pages stay live — this is how a finished prefill's full
        prompt pages become prefix-cache property instead of being
        recycled with the request)."""
        held = self._owned.get(owner, [])
        for p in pages:
            if p not in held:
                raise RuntimeError(
                    f"transfer of page {p} not owned by {owner!r}")
            held.remove(p)
        if not held:
            self._owned.pop(owner, None)
        self._owned.setdefault(new_owner, []).extend(pages)
        if self.ledger is not None and pages:
            self.ledger.record("transfer", owner, pages,
                               new_owner=new_owner)

    def owners(self) -> Dict[Hashable, Tuple[int, ...]]:
        """Snapshot of live ownership (diagnostics / cache audits)."""
        return {k: tuple(v) for k, v in self._owned.items()}

    def occupancy(self) -> Dict[str, int]:
        """Pool breakdown by owner class (r18 capacity timeline):
        ``inflight`` (request-owned) / ``prefix_device`` (prefix-cache
        chains) / ``dedup`` (cross-request content-shared pages, r23)
        / ``reserved`` (speculative capacity) / ``free``.
        Sums to ``num_pages`` by construction — the invariant
        tools/flight_inspect.py lints. Scrape/conn threads read this
        while the engine thread mutates; retry the benign
        dict-iteration race (the health-op discipline) — a class
        count pinned between retries stays self-consistent because it
        is recomputed whole."""
        infl = pfx = dedup = reserved = 0
        for attempt in range(3):
            infl = pfx = dedup = reserved = 0
            try:
                for owner, pages in list(self._owned.items()):
                    if isinstance(owner, tuple) and owner \
                            and owner[0] == "prefix":
                        pfx += len(pages)
                    elif isinstance(owner, tuple) and owner \
                            and owner[0] == "dedup":
                        dedup += len(pages)
                    else:
                        infl += len(pages)
                # inside the retry: summing _reserved.values() races
                # the same engine-thread mutations the _owned walk does
                reserved = self.reserved_total
                break
            except RuntimeError:
                continue
        # free NORMALIZED from the other classes (not read separately):
        # engine-thread reads are exact either way, and a scrape-side
        # racy read then still satisfies sum-to-pool instead of
        # presenting classes torn across two snapshots
        free = max(0, self.num_pages - infl - pfx - dedup - reserved)
        return {"inflight": infl, "prefix_device": pfx,
                "dedup": dedup, "reserved": reserved, "free": free}

    def check_no_leak(self) -> None:
        if self._owned or self._reserved or \
                len(self._free) != self.num_pages:
            msg = (
                f"page leak: {sum(map(len, self._owned.values()))} owned "
                f"by {sorted(self._owned, key=str)}, "
                f"{self.reserved_total} reserved by "
                f"{sorted(self._reserved, key=str)} with "
                f"{len(self._free)}/{self.num_pages} free")
            if self.ledger is not None:
                # forensics, not counts (r18): each dangling page's
                # retained ownership history — who alloc'd it, on
                # which step, why, and every transfer since
                msg += "\nledger forensics:\n" + self.ledger.forensics(
                    self._owned, self._reserved)
            raise RuntimeError(msg)


@dataclasses.dataclass
class RequestStats:
    """Per-request serving telemetry (time.monotonic timestamps).

    Filled by the engine across the request lifecycle and exposed on
    completion (the record serving/metrics.py aggregates — the
    per-request granularity VERDICT weak #5 asked for). Derived
    latencies return None until their inputs exist."""

    submit_t: float = 0.0
    admit_t: float = 0.0
    prefill_ms: float = 0.0
    first_token_t: float = 0.0
    finish_t: float = 0.0
    tokens_out: int = 0
    prompt_len: int = 0
    cached_pages: int = 0          # prefix-cache pages reused at admit
    cached_tokens: int = 0         # = cached_pages * page_size
    # hierarchical prefix cache (r15): pages restored from spill tiers
    # at admission (a subset of cached_pages — restored pages skip
    # their prefill exactly like device hits, at the cost of one
    # device_put + page-table splice, whose wall time is restore_ms)
    restored_pages: int = 0
    restored_host_pages: int = 0
    restored_disk_pages: int = 0
    restore_corrupt: int = 0       # corrupt blobs hit (fell back typed)
    restore_ms: float = 0.0
    # disaggregated serving (r20): pages spliced in whose blobs were
    # FETCHED from a peer replica over the wire (a subset of
    # restored_pages — the fetched-vs-restored split), and the wall
    # time the server's connection thread spent on the fetch RPC
    # (off the engine thread; decode never waits on the wire)
    handoff_pages: int = 0
    handoff_ms: float = 0.0
    prompt_pages: int = 0          # shareable full pages in the prompt
    cache_enabled: bool = False    # a prefix cache was configured
    prefill_attempts: int = 0      # 1 = first try succeeded
    prefill_chunks: int = 0        # prefill launches (1 = whole prefill)
    spec_steps: int = 0            # verify steps this request rode
    spec_drafted: int = 0          # draft tokens offered to verify
    spec_accepted: int = 0         # draft tokens accepted
    # memory observatory (r18): per-request page attribution — the
    # high-water mark of privately-owned pages (shared prefix pages
    # are the cache's) and the time integral of pages held (page *
    # seconds), maintained by the engine at admission, each step, and
    # final free. The serving_request_peak_pages histogram aggregates
    # the former.
    peak_pages: int = 0
    page_seconds: float = 0.0

    @property
    def acceptance_rate(self) -> Optional[float]:
        """Accepted / drafted over the request's verify steps."""
        if self.spec_drafted:
            return self.spec_accepted / self.spec_drafted
        return None

    @property
    def tokens_per_step(self) -> Optional[float]:
        """Decode tokens emitted per verify step (the speculative win:
        > 1 means the weight/KV stream amortized). The prefill-produced
        first token is excluded — it predates any verify step."""
        if self.spec_steps and self.tokens_out > 1:
            return (self.tokens_out - 1) / self.spec_steps
        return None

    @property
    def queue_delay_s(self) -> Optional[float]:
        if self.admit_t and self.submit_t:
            return self.admit_t - self.submit_t
        return None

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit → first generated token (includes queueing)."""
        if self.first_token_t and self.submit_t:
            return self.first_token_t - self.submit_t
        return None

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean per-output-token time after the first token."""
        if self.finish_t and self.first_token_t and self.tokens_out > 1:
            return ((self.finish_t - self.first_token_t)
                    / (self.tokens_out - 1))
        return None

    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["queue_delay_s"] = self.queue_delay_s
        out["ttft_s"] = self.ttft_s
        out["tpot_s"] = self.tpot_s
        out["acceptance_rate"] = self.acceptance_rate
        out["tokens_per_step"] = self.tokens_per_step
        return out


@dataclasses.dataclass
class DecodeRequest:
    """One generation request in the engine."""
    req_id: int
    prompt: np.ndarray                # [len] int32
    max_new_tokens: int
    eos_token: Optional[int] = None
    priority: int = 1                 # serving/scheduler.py Priority
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    done: bool = False
    # queued|prefill|prefill_partial|decoding|done|evicted|shed|failed
    # |deadline|stalled — prefill_partial is the chunked-prefill stage:
    # the slot holds pages and a PARTIAL prompt KV (prefill_done_len
    # tokens stored); it rides decode steps masked to the scratch page
    # until its last chunk lands
    state: str = "queued"
    # chunked prefill: prompt tokens whose KV is already stored
    # (prefix-cache hits count — shared pages and prior chunks are the
    # same "already stored" case); meaningful in prefill_partial
    prefill_done_len: int = 0
    # consecutive engine steps this request's next prefill chunk was
    # deferred by higher-class decode work (scheduler starvation bound)
    chunk_deferrals: int = 0
    stats: RequestStats = dataclasses.field(default_factory=RequestStats)
    on_token: Optional[Callable[[int, int, bool], None]] = None
    cache_keys: Tuple[Hashable, ...] = ()   # prefix-cache chain refs held
    bypass_count: int = 0             # times a later request jumped us
    # absolute time.monotonic() deadline (None = no deadline); carried
    # from the protocol's deadline_ms through admission, decode steps
    # and eviction so an expired request never holds pages
    deadline_t: Optional[float] = None
    # last time a token was delivered (stall watchdog input)
    last_emit_t: float = 0.0
    # end-to-end tracing (r16): the request's span tree
    # (serving/tracing.py RequestTrace; None = unsampled — the hot
    # path's only cost is this attribute check) and the currently open
    # lifecycle-stage span (queue -> prefill -> decode)
    trace: Any = None
    span: Any = None
    # disaggregated serving (r20): True marks a handoff-blocking
    # prefill job (a prefill-class replica's prefill_only request —
    # a decode replica is waiting on its chain), which the SLO
    # scheduler boosts by cfg.handoff_boost priority levels
    handoff: bool = False

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


class ContinuousBatchingEngine:
    """Fixed-slot continuous batching over one jitted paged decode step.

    ``num_pages`` sizes the shared pool; with
    num_pages < num_slots * max_pages_per_seq the engine oversubscribes
    slots against real memory and admission blocks on the free list —
    the page-recycling regime the tests pin. Greedy decoding (the
    deterministic serving mode; sampling belongs to generate())."""

    def __init__(self, model, num_slots: int = 4, page_size: int = 64,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 kv_int8: bool = False,
                 prompt_buckets: Sequence[int] = (),
                 scheduler=None, prefix_cache=None,
                 prefill_retry=None,
                 on_complete: Optional[Callable[["DecodeRequest"],
                                                None]] = None,
                 max_prefill_attempts: int = 3,
                 speculative=None, verify_retry="site",
                 stall_timeout_s: Optional[float] = None,
                 mesh=None,
                 prefill_chunk_tokens: Optional[int] = None,
                 fused_step: bool = True,
                 multi_step: int = 1,
                 inprogram: bool = True,
                 tracer=None, timeline_steps: int = 256,
                 capture_costs: bool = False,
                 page_ledger: bool = True,
                 ledger_events: int = 1024,
                 forecast_admission: bool = False,
                 weight_generation: int = 0):
        import jax.numpy as jnp

        from ..core.compile_cache import enable_compile_cache
        from ..nn.layer import functional_state
        from ..models.gpt import paged_cache_create

        # env-gated persistent compile cache (PADDLE_TPU_COMPILE_CACHE):
        # the engine's prefill-per-bucket + decode/verify programs are
        # exactly the compiles a restarted server pays again cold
        enable_compile_cache()
        self.model = model
        model.eval()
        # weight hot-swap (r24): the generation of the weights this
        # engine currently serves. swap_weights bumps it; the prefix
        # cache salts chain roots with it so KV from different
        # generations never splices.
        self.weight_generation = int(weight_generation)
        self.weight_swaps = 0
        # swap drain gate: while True, _admit is a no-op — active
        # slots finish and free, queued requests WAIT (nothing is
        # dropped), and a pending swap can reach num_active == 0
        # under continuous traffic. Owned by the serving layer.
        self.pause_admission = False
        cfg = model.config
        self.cfg = cfg
        # tensor-parallel serving (mesh=None = single-device, the
        # byte-for-byte pre-r10 behavior): weights shard per their
        # mp_layers pspecs, KV pools shard over heads, page table and
        # seq_lens stay replicated host state, and the one compiled
        # decode/verify/prefill step runs under GSPMD with the paged-
        # attention op head-sharded via shard_map. The allocator and
        # every host-side page-accounting invariant are untouched: a
        # page is a page on every shard.
        self.mesh = mesh
        self._mesh_axis = None
        self._kv_sharding = None
        self._state_shardings = None
        # identity cache for sharded weights: (kind, name) -> (source
        # array, its device_put result). An unchanged leaf transfers to
        # the mesh ONCE per engine lifetime; per-admission state
        # refreshes then cost dict lookups, not host->mesh copies.
        self._shard_cache: Dict = {}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..distributed.topology import SERVING_MODEL_AXIS
            axis = SERVING_MODEL_AXIS
            if axis not in mesh.axis_names:
                raise ValueError(
                    f"serving mesh must carry a {axis!r} axis "
                    f"(distributed.topology.make_serving_mesh); got "
                    f"axes {mesh.axis_names}")
            extra = [a for a in mesh.axis_names
                     if a != axis and mesh.shape[a] != 1]
            if extra:
                raise ValueError(
                    f"serving mesh axes {extra} must have size 1 "
                    f"(only {axis!r} shards the decode engine)")
            n = int(mesh.shape[axis])
            if cfg.num_heads % n:
                raise ValueError(
                    f"num_heads {cfg.num_heads} not divisible by mesh "
                    f"{axis}={n}")
            if cfg.vocab_size % n:
                raise ValueError(
                    f"vocab_size {cfg.vocab_size} not divisible by "
                    f"mesh {axis}={n} (VocabParallelEmbedding shards "
                    f"the vocab dim)")
            self._mesh_axis = axis
            # one spec serves pools ([P+1, page, H, D]) and scales
            # ([P+1, page, H]): dim 2 is the head dim in both
            self._kv_sharding = NamedSharding(
                mesh, PartitionSpec(None, None, axis))
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > int(cfg.max_seq_len):
            # the GPT position table (wpe) has exactly cfg.max_seq_len
            # rows: positions past it are an out-of-bounds gather whose
            # jnp fill-mode NaNs poison the shared scratch page and,
            # through the attention row max, every co-resident slot's
            # stream — fail typed at construction instead
            raise ValueError(
                f"max_seq_len={self.max_seq_len} exceeds the model's "
                f"position-embedding capacity "
                f"(cfg.max_seq_len={cfg.max_seq_len}); positions past "
                f"it would read garbage embeddings. Use a config with "
                f"a larger max_seq_len")
        self.max_pages = -(-self.max_seq_len // self.page_size)
        self.num_pages = int(num_pages if num_pages is not None
                             else num_slots * self.max_pages)
        self.kv_int8 = bool(kv_int8)
        if not prompt_buckets:
            bucket, prompt_buckets = self.page_size, []
            while bucket < self.max_seq_len:
                prompt_buckets.append(bucket)
                bucket *= 2
            prompt_buckets.append(self.max_seq_len)
        self.prompt_buckets = sorted(set(int(x) for x in prompt_buckets))

        # page ledger (r18, inference/page_ledger.py): every allocator
        # mutation appended to a bounded ring with owner/step/reason —
        # the memory-forensics plane. Default ON (host-side dict
        # appends next to jit launches; the memory_observatory bench
        # A/Bs it at ~1.0x ms/step); page_ledger=False is the
        # byte-for-byte pre-r18 allocator.
        if page_ledger:
            from .page_ledger import PageLedger
            self.ledger: Optional["PageLedger"] = PageLedger(
                capacity=int(ledger_events))
        else:
            self.ledger = None
        self.allocator = PageAllocator(self.num_pages,
                                       ledger=self.ledger)
        # byte-planning admission (r23): when True, _fits also charges
        # the forecast page-burn of the already-admitted fleet over
        # this request's expected lifetime (the r18 exhaustion
        # forecast over the step timeline) — a request is admitted
        # only when the POOL'S FUTURE, not just its instant free
        # count, accommodates it. Default False: byte-for-byte the
        # instant-occupancy gate.
        self.forecast_admission = bool(forecast_admission)
        self.forecast_denials = 0
        self._scratch = self.num_pages  # reserved page index
        dt = functional_state(model)["params"]["gpt.wte.weight"].dtype
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        self._nl = nl
        # one DISTINCT pool per layer (not nl references to one array:
        # the jitted step donates the pool buffers, and donating the
        # same buffer for two arguments is an error)
        protos = [paged_cache_create(
            1, self.num_pages, self.page_size, nh, hd, dt,
            self.max_pages, quantized=self.kv_int8,
            kv_sharding=self._kv_sharding) for _ in range(nl)]
        self._pools = {
            "k": [p.k_pages for p in protos],
            "v": [p.v_pages for p in protos],
            "ks": [p.k_scale for p in protos],
            "vs": [p.v_scale for p in protos],
        }
        # host-owned scheduler state
        self._table = np.full((self.num_slots, self.max_pages),
                              self._scratch, np.int32)
        self._lens = np.zeros((self.num_slots,), np.int32)
        self._cur = np.zeros((self.num_slots,), np.int32)
        self._slots: List[Optional[DecodeRequest]] = \
            [None] * self.num_slots
        self._queue: List[DecodeRequest] = []
        self._finished: Dict[int, DecodeRequest] = {}
        self._next_id = 0
        self._jnp = jnp
        self._decode_jit = None
        self._prefill_jits: Dict[bool, Any] = {}
        self._state_cache = None
        self.steps = 0
        # serving hooks (all optional; None = bare-engine behavior)
        self._scheduler = scheduler
        cache_ps = getattr(prefix_cache, "page_size", None)
        if cache_ps is not None and int(cache_ps) != self.page_size:
            # fail at construction, not as a page leak after the first
            # successful prefill's insert()
            raise ValueError(
                f"prefix_cache.page_size {cache_ps} != engine "
                f"page_size {self.page_size}")
        self._prefix_cache = prefix_cache
        # hierarchical prefix cache (r15): a cache carrying spill tiers
        # needs device IO — how to copy an evicted page's KV to host
        # (spill) and splice a restored blob into a fresh page. The
        # splice is one jitted donate-in-place scatter per restore
        # (models/gpt.py paged_page_splice), compiled once; the spill
        # read is one jitted stacked gather (same discipline).
        self._splice_jit = None
        self._gather_jit = None
        if getattr(prefix_cache, "tiers", None):
            prefix_cache.attach_device_io(self._read_page,
                                          self._splice_page)
        self._prefill_retry = prefill_retry
        self._on_complete = on_complete
        self.max_prefill_attempts = int(max_prefill_attempts)
        # stall watchdog: a slot that delivers no token for this long
        # is evicted with the typed "stalled" state instead of holding
        # its pages forever (None = off). Healthy engines emit a token
        # per active slot per step, so a stall only ever means the
        # step itself is failing or pathologically slow.
        self.stall_timeout_s = (None if stall_timeout_s is None
                                else float(stall_timeout_s))
        # chunked prefill (r11): None = whole-prefill admission (the
        # byte-for-byte pre-r11 behavior). A positive multiple of
        # page_size makes admission bind pages WITHOUT prefilling and
        # each step() advance at most one half-prefilled slot by one
        # page-aligned chunk of this many tokens (one fixed chunk
        # bucket -> one prefill compile) before the decode step — so
        # in-flight streams keep ticking while a long prompt trickles
        # in instead of stalling behind its whole suffix prefill.
        self.prefill_chunk_tokens: Optional[int] = None
        if prefill_chunk_tokens is not None:
            c = int(prefill_chunk_tokens)
            if c < self.page_size or c % self.page_size:
                raise ValueError(
                    f"prefill_chunk_tokens {c} must be a positive "
                    f"multiple of page_size {self.page_size} (chunks "
                    f"are page-aligned so every chunk boundary lands "
                    f"on a page boundary)")
            self.prefill_chunk_tokens = c
        # split EMAs (r11): the deadline admission gate's estimates.
        # decode_ema_s tracks ONLY the decode/verify jit call;
        # prefill_chunk_ema_s tracks one fixed-bucket prefill chunk
        # (constant-cost by construction), so a prefill-heavy step
        # can't poison the per-token estimate short requests are
        # gated on. step_ema_s remains as a back-compat alias.
        self.decode_ema_s: Optional[float] = None
        self.prefill_chunk_ema_s: Optional[float] = None
        # chunk-EMA warmup guard (the analog of decode's skip-first-
        # step rule): the first launch of each chunk-jit variant
        # (fresh / chained) is compile-dominated — recording it would
        # make _deadline_hopeless estimate seconds per chunk and shed
        # every deadline-carrying long prompt until the EMA decayed
        self._chunk_warm = {False: False, True: False}
        # engine-wide last-chunk-progress timestamp: the stall
        # watchdog's liveness signal for half-prefilled slots WAITING
        # their turn for the single per-step chunk budget (see
        # evict_stalled)
        self._last_chunk_t = 0.0
        # fused decode hot path (r13): True (the default) traces the
        # decode/prefill/verify programs through the fused kernels —
        # attention + out-projection folded into ONE op per layer
        # (models/gpt.py fused_decode -> ops paged_attention_fused)
        # and sampling streamed through the lm_head from the final
        # hidden row (nn/decode.py fused_sample_token), so the
        # [B, vocab] logits tensor never materializes in HBM. Greedy
        # outputs are BIT-IDENTICAL either way where the fused
        # REFERENCES run (the CPU lane — pinned); on TPU the Mosaic
        # fused kernels mimic the unfused lowering's precision but
        # cross-mode bit-parity there is chip-pending validation
        # (ops/pallas/paged_attention.py paged_attention_fused).
        # False is byte-for-byte the pre-r13 trace — the same
        # escape-hatch pattern as mesh=None / prefill_chunk_tokens=None.
        self.fused_step = bool(fused_step)
        # device-resident multi-step decode (r19, ROADMAP item 2):
        # multi_step=N wraps N fused decode steps in ONE on-device
        # lax.while_loop program (models/gpt.py multi_step_decode) —
        # early exit on EOS via masked carry, KV appends against
        # PRE-BOUND page budgets (admission reserves the growth pages;
        # _dispatch_macro converts reservation -> physical pages before
        # every launch, which cannot fail by the PR 4 contract), and a
        # device-side token ring [B, N] read back once per launch.
        # Launches are dispatch-then-drain: step K's results are
        # drained at boundary K+1, so token delivery/tracing/metrics
        # and the serving loop's inbox work overlap the device compute
        # (JAX async dispatch; no new threads). Admission and chunked
        # prefill run at the boundary, in the drain->dispatch gap —
        # they rewrite the launch's table/lens/cur inputs and donate
        # the pools, so they cannot run under an in-flight launch;
        # that gap is the N-vs-TTFT trade. multi_step=1 (the default)
        # is byte-for-byte the per-token engine. r22 (in-program inner
        # loop) moves speculative verify and chained prefill chunks
        # INSIDE the macro program when eligible (see _spec_inprogram /
        # _chunk_inprogram below); `inprogram=False` pins the r19
        # boundary-interleaved behavior as the bisection rung.
        self.multi_step = int(multi_step)
        if self.multi_step < 1:
            raise ValueError(
                f"multi_step must be >= 1 (1 = per-token decode); got "
                f"{multi_step}")
        self.inprogram = bool(inprogram)
        # macro program variants keyed by has_chunk (a launch with a
        # scheduled in-program chunk is a different traced program than
        # a decode/verify-only one; both are built at most once)
        self._multi_jits: Dict[bool, Any] = {}
        # in-flight macro launch: device handles + the slot->request
        # snapshot the drain folds back (None = nothing dispatched)
        self._pending_macro: Optional[Dict[str, Any]] = None
        # drained-but-undelivered (req, token, done) emissions, in the
        # exact (in-macro step, slot) order the per-token engine would
        # have streamed them; delivered AFTER the next launch is
        # dispatched (host/device overlap), and flushed per-request by
        # _notify_complete so streamed tokens always precede the
        # completion notification on every terminal path
        self._pending_emit: List[Tuple] = []
        self.macro_launches = 0
        # macro-EMA warmup: the first launch is compile-dominated
        # (the skip-first-step rule, applied per program kind)
        self._macro_warm = False
        # engine-wide last-macro-drain timestamp: the stall watchdog's
        # liveness signal for decoding slots between boundaries (a
        # healthy macro delivers every decoding slot's tokens at each
        # drain; a broken one lets this go stale and the stall fires)
        self._last_macro_t = 0.0
        # page-growth discipline: multi-step shares the speculative
        # reserve-then-grow contract — admission binds only the
        # prefill-covering pages and RESERVES the rest, macro dispatch
        # grows each slot's page set to cover its next min(N, rem)
        # positions out of that reservation (guaranteed to succeed)
        self._reserve_growth = (speculative is not None or
                                self.multi_step > 1)
        # traced-program op counts per jitted step kind (the launch
        # counter: dispatch.count_op_calls around each jit call counts
        # the ops traced into the program on a (re)trace, zero on the
        # compiled fast path) — the fused_decode A/B's currency and
        # the serving_step_programs gauge's source
        self.step_programs: Dict[str, int] = {}
        # end-to-end tracing (r16): a serving/tracing.py SpanTracer
        # (None = off, the default — every hook degrades to one
        # attribute check; sampling happens once per request at
        # submit, so there is NO per-token cost for unsampled work)
        self._tracer = tracer
        # step timeline (r16): a fixed-size ring of per-step records —
        # programs launched by kind, decode/verify/chunk/splice wall
        # ms, slot occupancy and page pressure. Always on: one small
        # dict per ENGINE STEP (never per token) next to a jit launch.
        self.timeline: "collections.deque" = collections.deque(
            maxlen=max(1, int(timeline_steps)))
        # drained-macro attribution for the NEXT _tl_commit (r19)
        self._tl_macro: Optional[Dict[str, Any]] = None
        # cumulative program launches by kind (every jit call — 1 per
        # launch, unlike step_programs which records traced-op counts)
        self.programs_launched: Dict[str, int] = {}
        self._tl_programs: Dict[str, int] = {}
        self._tl_ms: Dict[str, float] = {}
        # program cost capture (r16 satellite): at each program kind's
        # first (re)trace, run jit.lower(...).cost_analysis() on stub
        # avals — flops / bytes-accessed estimates for the
        # serving_program_* gauges (replacing the r10 collective-bytes
        # stub). Engine-thread only (bind_state tracing is not
        # thread-safe) and OFF by default: the extra abstract trace
        # per kind (~decode-trace cost) is only worth paying where the
        # numbers are scraped — the server enables it.
        self._capture_costs = bool(capture_costs)
        self._program_costs: Dict[str, Dict] = {}
        self._kv_dtype = dt
        # speculative decoding (inference/speculative.py): draft k
        # tokens per step, verify all k+1 in ONE forward, emit the
        # longest accepted prefix + 1. Greedy stays bit-identical to
        # the vanilla engine; OFF by default.
        self._spec_cfg = None
        self._spec_draft = None
        self._verify_jit = None
        self._spec_key = None
        if speculative is not None:
            from .speculative import as_spec_config
            self._spec_cfg = as_spec_config(speculative)
            self._spec_draft = self._spec_cfg.build_draft()
            if verify_retry == "site":
                from ..distributed.resilience import get_retry_policy
                verify_retry = get_retry_policy("serving.verify")
            self._verify_retry = verify_retry
        else:
            self._verify_retry = None
        # r22 in-program eligibility. Speculative verify moves inside
        # the macro while_loop only when every piece has a device twin:
        # multi_step > 1 (there IS a macro program), greedy verify
        # (temperature 0 — the bit-identical serving mode; residual
        # resampling stays at the boundary), and a draft source
        # expressible as pure array math over the stored history
        # (ngram/self — ModelDraft and CallableDraft run host code).
        # Chunked prefill moves inside only when speculation either is
        # off or also moved inside (a half-in half-out split would put
        # the boundary back).
        self._spec_inprogram = False
        self._spec_device_draft = None
        if (self.inprogram and self.multi_step > 1
                and self._spec_cfg is not None
                and float(self._spec_cfg.temperature) == 0.0):
            from .speculative import device_draft_params
            p = device_draft_params(self._spec_draft)
            if p is not None:
                self._spec_inprogram = True
                self._spec_device_draft = p
        self._chunk_inprogram = (
            self.inprogram and self.multi_step > 1
            and self.prefill_chunk_tokens is not None
            and (self._spec_cfg is None or self._spec_inprogram))

    # -- request lifecycle -------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               eos_token: Optional[int] = None, priority: int = 1,
               on_token: Optional[Callable[[int, int, bool], None]] = None,
               deadline_t: Optional[float] = None,
               trace=None, trace_ctx: Optional[Dict] = None,
               handoff: bool = False,
               handoff_info: Optional[Dict] = None) -> int:
        """``trace``: an existing RequestTrace to CONTINUE (resurrection
        replay resubmits the in-flight request onto the same tree);
        ``trace_ctx``: a wire context from an upstream hop (the
        failover router) that forces sampling and links this request's
        root under the upstream span. With neither, the engine's own
        tracer (if any) makes the sampling decision.

        Disaggregated serving (r20): ``handoff=True`` marks a
        handoff-blocking prefill job (scheduler boost);
        ``handoff_info={"ms": ..., "bytes": ...}`` records the wire
        fetch the server's connection thread already performed for
        this request (the fetched blobs sit in the prefix cache's
        tiers; admission splices them via restore_from_spill)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself produces the first token)")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prompt bucket {self.prompt_buckets[-1]}")
        need = -(-(len(prompt) + max_new_tokens) // self.page_size)
        if need > self.num_pages:
            # would block the FIFO head forever — no amount of
            # recycling frees pages that never existed
            raise ValueError(
                f"request needs {need} pages but the pool has only "
                f"{self.num_pages}; raise num_pages or shrink the "
                f"request")
        req = DecodeRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token, priority=int(priority),
                            on_token=on_token,
                            deadline_t=(None if deadline_t is None
                                        else float(deadline_t)),
                            handoff=bool(handoff))
        req.stats.submit_t = time.monotonic()
        req.stats.prompt_len = len(prompt)
        if handoff_info:
            req.stats.handoff_ms = float(handoff_info.get("ms", 0.0))
        self._next_id += 1
        tr = trace
        if tr is None and self._tracer is not None:
            if trace_ctx is not None:
                tr = self._tracer.start(
                    "request", ctx=trace_ctx, req_id=req.req_id,
                    prompt_len=len(prompt),
                    max_new=int(max_new_tokens))
            elif self._tracer.sample():
                tr = self._tracer.start(
                    "request", sampled=True, req_id=req.req_id,
                    prompt_len=len(prompt),
                    max_new=int(max_new_tokens))
        if tr is not None:
            req.trace = tr
            req.span = tr.begin("queue", parent=tr.anchor,
                                req_id=req.req_id,
                                priority=int(priority),
                                prompt_len=len(prompt))
        self._queue.append(req)
        return req.req_id

    def result(self, req_id: int, pop: bool = False
               ) -> Optional[np.ndarray]:
        req = (self._finished.pop(req_id, None) if pop
               else self._finished.get(req_id))
        return None if req is None else req.tokens

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    @property
    def free_pages(self) -> int:
        return self.allocator.free_count

    @property
    def step_ema_s(self) -> Optional[float]:
        """Back-compat alias: r11 split the old blended step EMA into
        ``decode_ema_s`` (decode/verify jit only) and
        ``prefill_chunk_ema_s`` (one fixed-bucket prefill chunk)."""
        return self.decode_ema_s

    @step_ema_s.setter
    def step_ema_s(self, value: Optional[float]) -> None:
        self.decode_ema_s = value

    @property
    def prefill_debt_tokens(self) -> int:
        """Outstanding prefill work in tokens: the un-stored prompt
        suffix of every half-prefilled slot plus every queued prompt
        (an upper bound — future prefix-cache hits may shrink it).
        The serving layer exports this as the
        ``serving_prefill_debt_tokens`` gauge."""
        debt = sum(len(r.prompt) - r.prefill_done_len
                   for r in self._slots
                   if r is not None and r.state == "prefill_partial")
        debt += sum(len(r.prompt) for r in self._queue)
        return debt

    # -- jitted device programs -------------------------------------------

    def _caches(self, pools, table, lens):
        from ..models.gpt import PagedKVCache
        return [PagedKVCache(pools["k"][i], pools["v"][i],
                             pools["ks"][i], pools["vs"][i],
                             table, lens) for i in range(self._nl)]

    def _fresh_state(self, refresh: bool = False):
        """Model functional state (params AND buffers — converted
        layers hold int8 weights as buffers) for the jitted calls.
        Re-read at every ADMISSION (refresh=True) so post-construction
        weight mutation (set_state_dict, convert_to_weight_only_int8)
        is served, not silently ignored — a structural change simply
        retraces via the new argument pytree (the r5 stale-cache
        lesson). The per-token decode step reuses the cached dict:
        rebuilding hundreds of entries per generated token is pure
        host overhead on the hot path."""
        if refresh or self._state_cache is None:
            from ..nn.layer import functional_state
            self._state_cache = self._shard_state(
                functional_state(self.model))
        return self._state_cache

    def _shard_state(self, state):
        """Place the functional state on the serving mesh per each
        weight's mp_layers pspec (mesh=None: passthrough). Transfers
        are identity-cached, so only leaves that actually changed since
        the last refresh (set_state_dict, int8 conversion) move; a
        structural change (new buffer names) recomputes the sharding
        tree — the same retrace-don't-stale contract `_fresh_state`
        documents."""
        if self.mesh is None:
            return state
        import jax

        from ..nn.layer import functional_state_shardings
        if self._state_shardings is None:
            self._state_shardings = functional_state_shardings(
                self.model, self.mesh)
        out: Dict[str, Dict] = {}
        missed: List = []  # (kind, name, val, sharding)
        for kind in ("params", "buffers"):
            grp = {}
            for name, val in state[kind].items():
                hit = self._shard_cache.get((kind, name))
                if hit is not None and hit[0] is val:
                    grp[name] = hit[1]
                    continue
                sh = self._state_shardings[kind].get(name)
                if sh is None:  # structural change: new leaf appeared
                    self._state_shardings = functional_state_shardings(
                        self.model, self.mesh)
                    sh = self._state_shardings[kind][name]
                missed.append((kind, name, val, sh))
                grp[name] = None  # filled from the batched transfer
            out[kind] = grp
        if missed:
            # ONE batched transfer for every cache miss: on engine
            # build/resurrection all leaves miss, and per-leaf
            # device_put dispatch is serial host overhead
            puts = jax.device_put([v for _, _, v, _ in missed],
                                  [s for _, _, _, s in missed])
            for (kind, name, val, _), put in zip(missed, puts):
                self._shard_cache[(kind, name)] = (val, put)
                out[kind][name] = put
        # prune leaves that vanished from the state (e.g. fp32 params
        # replaced by int8 buffers when convert_to_weight_only_int8
        # swaps layers): a stale entry pins BOTH the host array and its
        # on-mesh copy for the engine lifetime — roughly a full dead
        # model of HBM on exactly the deployments mesh= targets
        live = {(k, n) for k in ("params", "buffers") for n in out[k]}
        for stale in [k for k in self._shard_cache if k not in live]:
            del self._shard_cache[stale]
        return out

    def swap_weights(self, state_dict,
                     generation: Optional[int] = None
                     ) -> Dict[str, Any]:
        """Weight hot-swap (r24): replace the model's weights between
        steps with a fully-validated state dict, bump the weight
        generation, and re-salt the prefix-cache chain keys so KV from
        the old weights misses by construction.

        Validate-then-swap is ATOMIC: the incoming tree is checked
        against the model's own state dict (exact key set, exact
        shapes, exact dtypes) BEFORE any tensor is touched —
        ``set_state_dict`` raises mid-apply on a shape mismatch and
        silently coerces dtypes, so the only safe swap is one that
        cannot hit either path. Any validation failure, and any
        in-flight work (active slots or an undrained macro launch), is
        a typed :class:`SwapFailed` with the old weights still serving
        and the old generation pinned. Queued-but-unadmitted requests
        survive the swap: their memoized chain keys are invalidated so
        their prefills insert under the NEW generation's keys.

        Returns ``{"generation", "leaves", "swap_ms"}`` on success."""
        from ..distributed.fault_inject import fault_point
        t0 = time.monotonic()
        gen = int(generation) if generation is not None \
            else self.weight_generation + 1
        if gen == self.weight_generation:
            raise SwapFailed(
                f"generation {gen} is already serving; a swap must "
                f"move to a new weight generation")
        # macro boundary (r19): a dispatched-but-undrained launch still
        # reads the OLD weights — drain it so the swap lands between
        # launches, never under one
        self._flush_macro()
        if self.num_active:
            raise SwapFailed(
                f"engine busy: {self.num_active} active slot(s) — "
                f"drain in-flight requests before swapping (old "
                f"requests finish on old weights)")
        own = self.model.state_dict(include_non_persistable_buffer=True)
        got = dict(state_dict)
        missing = [k for k in own if k not in got]
        extra = [k for k in got if k not in own]
        if missing or extra:
            raise SwapFailed(
                f"state-dict structure mismatch: missing "
                f"{sorted(missing)[:8]}, unexpected "
                f"{sorted(extra)[:8]} — a partial apply would serve "
                f"mixed tensors")
        bad = []
        for name, target in own.items():
            arr = np.asarray(getattr(got[name], "value", got[name]))
            if tuple(arr.shape) != tuple(target.shape):
                bad.append(f"{name}: shape {tuple(arr.shape)} vs "
                           f"{tuple(target.shape)}")
            elif np.dtype(arr.dtype) != np.dtype(target.dtype):
                bad.append(f"{name}: dtype {arr.dtype} vs "
                           f"{target.dtype}")
        if bad:
            raise SwapFailed(
                f"state-dict tree mismatch ({len(bad)} leaves): "
                f"{bad[:4]}")
        # the apply fault site fires AFTER validation and BEFORE the
        # first tensor write: an injected abort here proves the
        # all-or-nothing contract (no tensor touched yet)
        fault_point("swap.apply")
        self.model.set_state_dict(got)
        # identity cache: only changed leaves re-transfer to the mesh
        self._fresh_state(refresh=True)
        self.weight_generation = gen
        self.weight_swaps += 1
        if self._prefix_cache is not None:
            with self._led("swap"):
                self._prefix_cache.set_generation(gen, self.allocator)
        # queued requests memoized their chain keys under the OLD
        # generation's salt (match() caches on the request); drop the
        # memos so post-swap admission hashes fresh
        for req in self._queue:
            if hasattr(req, "_pfx_chain"):
                del req._pfx_chain
        return {"generation": gen,
                "leaves": len(own),
                "swap_ms": round((time.monotonic() - t0) * 1e3, 3)}

    def _head_ctx(self):
        """Trace-time mesh routing for the jitted programs: under a
        mesh, every `paged_attention` call inside the traced body
        dispatches head-sharded via shard_map (each device runs the
        standard kernel-selection path on its H/N-head slice), and the
        mp_layers ACTIVATION constraints are disabled — they pin to the
        global hybrid (training) mesh, which is a different device set
        than the serving mesh whenever a fleet group is live in the
        process (the PR-1 leaked-mesh failure mode: "incompatible
        devices" at trace time). The serving mesh carries only mp, so
        GSPMD infers the activation layouts from the weight and KV-pool
        shardings instead.

        mesh=None traces ALSO disable the constraints: the single-device
        engine never wants hybrid-mesh activation constraints either,
        and a live fleet group in the same process (training + serving,
        or a group leaked by an earlier test module) otherwise pins the
        decode traces to the training mesh — observed as WRONG decode
        outputs, not a trace error. In a clean process hcg is None and
        _constrain is already a no-op, so single-device behavior is
        unchanged."""
        from ..distributed.mp_layers import no_sharding_constraints
        if self.mesh is None:
            return no_sharding_constraints()
        from ..ops.pallas.paged_attention import head_sharding
        ctx = contextlib.ExitStack()
        ctx.enter_context(head_sharding(self.mesh, self._mesh_axis))
        ctx.enter_context(no_sharding_constraints())
        return ctx

    def _fuse_ctx(self):
        """Trace-time fused-kernel routing (r13): under ``fused_step``
        the traced body's paged-attention calls fold their epilogue
        into `paged_attention_fused` (models/gpt.py fused_decode);
        fused_step=False returns a null context so the trace is
        byte-for-byte the pre-r13 program."""
        if not self.fused_step:
            return contextlib.nullcontext()
        from ..models.gpt import fused_decode
        return fused_decode()

    def _fused_head(self):
        """``(weight, transpose_y, bias)`` of a streamable lm_head, or
        None when fusion is off or the model's head is not a plain fp
        matmul (callers then keep the exact unfused logits path).
        Evaluated INSIDE the traced body under bind_state, so the
        weights are the jit's ARGUMENTS, never closure constants, and
        a post-construction conversion (int8) re-decides at the
        retrace the new state pytree forces."""
        if not self.fused_step:
            return None
        if not hasattr(self.model, "decode_hidden"):
            return None
        hp = getattr(self.model, "head_params", None)
        return None if hp is None else hp()

    def _record_programs(self, kind: str, count: int) -> None:
        """Record a (re)trace's program op count; the compiled fast
        path counts zero and keeps the last traced figure. Every call
        is also one program LAUNCH of ``kind`` — the step timeline's
        per-kind launch currency (r16)."""
        if count:
            self.step_programs[kind] = count
        self.programs_launched[kind] = \
            self.programs_launched.get(kind, 0) + 1
        self._tl_programs[kind] = self._tl_programs.get(kind, 0) + 1

    # -- end-to-end tracing hooks (r16) -------------------------------------
    #
    # Every hook is a `req.trace is None` check when tracing is off —
    # the ~zero-cost contract. Stage spans (queue -> prefill -> decode)
    # live on req.span; per-step work is appended as pre-timed closed
    # spans (RequestTrace.add), so the per-slot cost of a traced step
    # is one list append, with no extra clock reads per slot.

    def _tr_end(self, req: DecodeRequest, **args) -> None:
        """Close the request's current lifecycle-stage span (no-op for
        unsampled requests); stage OPENS stay at their sites, where
        the stage-specific args live."""
        tr = req.trace
        if tr is not None and req.span is not None:
            tr.end(req.span, **args)
            req.span = None

    # -- page ledger + per-request page attribution (r18) -------------------

    def _led(self, reason: str, req_id: Optional[int] = None):
        """Ledger reason context for a page-moving code path (no-op
        null context with the ledger off)."""
        if self.ledger is None:
            return contextlib.nullcontext()
        return self.ledger.why(reason, req_id)

    def ledger_tail(self, n: int = 256) -> List[Dict[str, Any]]:
        """The ledger ring's most recent events (flight bundles and
        the server's ``capacity`` op); [] with the ledger off."""
        return [] if self.ledger is None else self.ledger.tail(n)

    def _account_req_pages(self, req: DecodeRequest,
                           now: Optional[float] = None) -> None:
        """Fold the request's CURRENT private page holding into its
        peak-pages / page-seconds attribution. Called at admission,
        once per engine step (_tl_commit), and right before the final
        free, so one-step requests still record their peak."""
        owned = len(self.allocator._owned.get(req.req_id, ()))
        st = req.stats
        if owned > st.peak_pages:
            st.peak_pages = owned
        now = time.monotonic() if now is None else now
        last = getattr(req, "_pages_t", None)
        if last is not None and owned:
            st.page_seconds += owned * max(0.0, now - last)
        req._pages_t = now

    def capacity_snapshot(self) -> Dict[str, Any]:
        """Point-in-time capacity card (the server's ``capacity`` op
        and flight bundles): pool occupancy by owner class (sums to
        num_pages), spill-tier residency, and ledger stats. Host-side
        ints only — safe from any thread, like the health gauges."""
        occ = self.allocator.occupancy()
        out: Dict[str, Any] = {
            "num_pages": int(self.num_pages),
            "page_size": int(self.page_size),
            "occupancy": occ,
            "used_fraction": round(
                1.0 - occ["free"] / self.num_pages, 4)
            if self.num_pages else 0.0,
            "steps": int(self.steps),
            "forecast_admission": bool(self.forecast_admission),
            "forecast_denials": int(self.forecast_denials),
        }
        pc = self._prefix_cache
        evictable = 0
        if pc is not None:
            # refcount-0 cache pages are reclaimed on demand at every
            # admission (evict_until) — a warm inclusive cache
            # legitimately fills the pool, so the PRESSURE-relevant
            # figure is the unreclaimable remainder, not raw used
            for _ in range(3):  # conn-thread read vs engine mutation
                try:
                    evictable = int(pc.evictable_pages())
                    break
                except RuntimeError:
                    continue
        out["evictable_pages"] = evictable
        out["unreclaimable_pages"] = max(
            0, self.num_pages - occ["free"] - evictable)
        out["unreclaimable_fraction"] = round(
            out["unreclaimable_pages"] / self.num_pages, 4) \
            if self.num_pages else 0.0
        if pc is not None and getattr(pc, "tiers", None):
            for t in pc.tiers:
                out[f"{t.name}_tier_pages"] = int(t.blob_count)
                out[f"{t.name}_tier_bytes"] = int(t.occupancy_bytes)
        if self.ledger is not None:
            out["ledger"] = self.ledger.stats()
        return out

    # -- step timeline + program cost capture (r16) -------------------------

    def _tl_commit(self, t_step: float) -> None:
        """Append one fixed-size step-timeline record (bounded ring)."""
        now = time.monotonic()
        # per-request page attribution (r18): one pass over the slots
        # per STEP (never per token) keeps peak-pages/page-seconds
        # current for long-running requests
        for r in self._slots:
            if r is not None:
                self._account_req_pages(r, now)
        entry: Dict[str, Any] = {
            "step": self.steps,
            "t_us": t_step * 1e6,
            "ms": round((now - t_step) * 1e3, 4),
            "programs": self._tl_programs,
            "slots_active": self.num_active,
            "slots_decoding": sum(
                1 for r in self._slots
                if r is not None and r.state == "decoding"),
            "queued": len(self._queue),
            "free_pages": self.allocator.free_count,
            "reserved_pages": self.allocator.reserved_total,
            # capacity timeline (r18): pool breakdown by owner class
            # (inflight/prefix_device/reserved/free — sums to the pool
            # size); the capacity op's forecast reads the free deltas
            "occupancy": self.allocator.occupancy(),
        }
        pc = self._prefix_cache
        if pc is not None and getattr(pc, "tiers", None):
            for t in pc.tiers:
                entry[f"{t.name}_tier_pages"] = int(t.blob_count)
        for k, v in self._tl_ms.items():
            entry[k] = round(v, 4)
        # multi-step decode (r19): the boundary that drained a macro
        # launch marks its entry with the launch's attribution
        # (per_token_timeline() reconstructs per-step rows from it)
        if self._tl_macro is not None:
            entry["macro"] = self._tl_macro
            self._tl_macro = None
        self.timeline.append(entry)

    def step_timeline(self) -> List[Dict[str, Any]]:
        """Snapshot of the per-step ring (oldest first) — the server's
        ``trace``/``stats`` ops and the goodput bench read this."""
        return list(self.timeline)

    def flight_summary(self) -> Dict[str, Any]:
        """JSON-safe engine state card for the crash flight recorder
        (r17): the numbers a postmortem wants next to the timeline
        ring — occupancy, page pressure, EMAs, launch totals, and the
        feature flags that shaped the traced programs. Host-side ints
        and floats only; safe to call from a dying engine."""
        return {
            "steps": int(self.steps),
            "num_slots": int(self.num_slots),
            "num_active": int(self.num_active),
            "num_queued": int(self.num_queued),
            "num_pages": int(self.num_pages),
            "free_pages": int(self.free_pages),
            "reserved_pages": int(self.allocator.reserved_total),
            "page_size": int(self.page_size),
            "max_seq_len": int(self.max_seq_len),
            "decode_ema_ms": (None if self.decode_ema_s is None
                              else round(self.decode_ema_s * 1e3, 3)),
            "prefill_chunk_ema_ms": (
                None if self.prefill_chunk_ema_s is None
                else round(self.prefill_chunk_ema_s * 1e3, 3)),
            "prefill_debt_tokens": int(self.prefill_debt_tokens),
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "fused_step": bool(self.fused_step),
            "multi_step": int(self.multi_step),
            "macro_launches": int(self.macro_launches),
            "speculative": self._spec_cfg is not None,
            "mesh": self.mesh_info(),
            "programs_launched": dict(self.programs_launched),
            "step_programs": dict(self.step_programs),
            "ledger_events": (None if self.ledger is None
                              else int(self.ledger.seq)),
        }

    def _tl_add_ms(self, key: str, seconds: float) -> None:
        self._tl_ms[key] = self._tl_ms.get(key, 0.0) + seconds * 1e3

    def _capture_cost(self, kind: str, jitfn, args: Tuple) -> None:
        """Capture flops / bytes-accessed estimates for ``kind`` from
        ``jit.lower(...).cost_analysis()`` on stub avals (no compile,
        no execution) — once per program kind, at (re)trace time, on
        the ENGINE thread (bind_state substitution is process-global,
        so a scrape thread must never trace the model concurrently).
        These feed the serving_program_* gauges that replace the r10
        ``serving_mesh_collective_bytes`` 0-stub; the chip-MEASURED
        collective traffic still needs an on-chip profiler session
        (chip-pending, as before)."""
        if not self._capture_costs or kind in self._program_costs:
            return
        import jax

        def stub(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sh = getattr(x, "sharding", None)
                if sh is not None and self.mesh is not None:
                    # host-side args (page table, lens, tokens) land
                    # on ONE device in the live call and jax replicates
                    # them; an abstract lower() has no auto-placement,
                    # so stub them replicated over the mesh or the
                    # mixed device sets fail the lowering
                    try:
                        if len(sh.device_set) == 1:
                            from jax.sharding import (NamedSharding,
                                                      PartitionSpec)
                            sh = NamedSharding(self.mesh,
                                               PartitionSpec())
                    except Exception:
                        sh = None
                try:
                    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                sharding=sh)
                except TypeError:
                    return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        try:
            stubs = jax.tree_util.tree_map(stub, args)
            ca = jitfn.lower(*stubs).cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            self._program_costs[kind] = {
                "flops": float(ca.get("flops") or 0.0),
                "bytes_accessed": float(ca.get("bytes accessed")
                                        or 0.0),
            }
        except Exception as e:  # cost capture must never break a step
            self._program_costs[kind] = {
                "error": f"{type(e).__name__}: {e}"}

    def program_costs(self) -> Dict[str, Dict]:
        """Per-program-kind cost estimates captured so far (empty
        until the first traced launch, or with capture off)."""
        return dict(self._program_costs)

    def mesh_collective_bytes_estimate(self) -> Optional[float]:
        """Estimated per-decode-step collective traffic under the
        serving mesh (None = single-device): the mp-partitioned
        contractions all-reduce their partial sums — 2 row-parallel
        reductions per layer (attention out-projection + MLP
        down-projection) plus the sampled-head reduction — and a ring
        all-reduce moves ``2 * (mp-1)/mp`` of the tensor bytes per
        device. The per-program flops/bytes figures come from
        ``program_costs`` (cost_analysis); the chip-MEASURED value
        remains chip-pending (xprof collective stats)."""
        if self.mesh is None:
            return None
        mp = int(self.mesh.shape[self._mesh_axis])
        if mp <= 1:
            return 0.0
        import numpy as _np
        itemsize = _np.dtype(self._kv_dtype).itemsize
        act = self.num_slots * int(self.cfg.hidden_size) * itemsize
        return float((2 * self._nl + 1) * act * 2 * (mp - 1) / mp)

    def _constrain_pools(self, pools):
        """Pin the returned pools to the engine's KV sharding (heads
        over the model axis; scales drop the trailing head-dim axis).
        Without this GSPMD is free to pick a different output layout,
        which would make the next step's donated inputs mismatch the
        compiled program and ping-pong the jit cache."""
        if self.mesh is None:
            return pools
        import jax
        # ONE definition of the KV layout: the same sharding the pools
        # were created under in __init__ (heads over the model axis —
        # P(None, None, mp) hits dim 2, the head dim of both the 4-D
        # pools and the 3-D scale pools)
        spec = self._kv_sharding

        def pin(xs):
            return [None if x is None
                    else jax.lax.with_sharding_constraint(x, spec)
                    for x in xs]

        return {"k": pin(pools["k"]), "v": pin(pools["v"]),
                "ks": pin(pools["ks"]), "vs": pin(pools["vs"])}

    # -- spill-tier device IO (r15) -----------------------------------------

    def _read_page(self, page: int) -> List[Tuple]:
        """Copy one pool page device→host for the prefix cache's spill
        tier: per layer (k, v, k_scale, v_scale) numpy blocks. Runs at
        eviction time on the engine thread; indexing the live pools is
        a read, so the donated buffers are untouched. The per-layer
        slices are stacked in ONE jitted gather so the spill costs one
        launch plus one transfer per pool KIND — not 2-4 sequential
        device round-trips per LAYER (the batched-splice discipline,
        applied to the read side)."""
        import jax

        jnp = self._jnp
        if self._gather_jit is None:
            def gather(pools, pg):
                k = jnp.stack([p[pg] for p in pools["k"]])
                v = jnp.stack([p[pg] for p in pools["v"]])
                ks = vs = None
                if self.kv_int8:
                    ks = jnp.stack([p[pg] for p in pools["ks"]])
                    vs = jnp.stack([p[pg] for p in pools["vs"]])
                return k, v, ks, vs

            self._gather_jit = jax.jit(gather)
        if self.ledger is not None:
            # spill-side device IO: the page's KV is leaving the
            # device for a spill tier (the cache decides which)
            self.ledger.record("spill", None, pages=[int(page)])
        k, v, ks, vs = self._gather_jit(
            self._pools, jnp.asarray(page, jnp.int32))
        k, v = np.asarray(k), np.asarray(v)
        ks = None if ks is None else np.asarray(ks)
        vs = None if vs is None else np.asarray(vs)
        return [(k[i], v[i],
                 None if ks is None else ks[i],
                 None if vs is None else vs[i])
                for i in range(self._nl)]

    def _splice_page(self, pages: Sequence[int],
                     layers_list: Sequence[Sequence[Tuple]]) -> None:
        """Restore a run of spilled pages in ONE batched device call:
        stack the per-page/per-layer host blocks and scatter them into
        every pool through a single jitted donate-in-place program
        (models/gpt.py paged_page_splice). The page indices are
        traced, and the batch is padded to a power-of-two bucket
        targeting the SCRATCH page (whose content is garbage by
        contract — masked writes land there every step), so the jit
        compiles once per bucket size, not once per restore shape.
        This is the whole restore-vs-reprefill trade: one device_put
        plus one scatter launch against the suffix prefill it
        replaces."""
        import jax

        jnp = self._jnp
        n = len(pages)
        nb = 1
        while nb < n:
            nb *= 2
        pad = nb - n

        def stack(idx):
            blocks = [np.stack([layers[i][idx] for layers in
                                layers_list]) for i in range(self._nl)]
            out = np.stack(blocks)            # [nl, n, page, ...]
            if pad:
                z = np.zeros(out.shape[:1] + (pad,) + out.shape[2:],
                             out.dtype)
                out = np.concatenate([out, z], axis=1)
            return out

        k, v = stack(0), stack(1)
        ks = vs = None
        if self.kv_int8:
            ks, vs = stack(2), stack(3)
        page_idx = np.asarray(list(pages) + [self._scratch] * pad,
                              np.int32)
        if self._splice_jit is None:
            from ..models.gpt import paged_page_splice

            def splice(pools, pg, kb, vb, ksb, vsb):
                with jax.named_scope("pt.page_splice"):
                    return self._constrain_pools(
                        paged_page_splice(pools, pg, kb, vb, ksb, vsb))

            self._splice_jit = jax.jit(splice, donate_argnums=(0,))
        from ..dispatch import count_op_calls
        if self.ledger is not None:
            # restore-side device IO: one batched splice writes the
            # whole contiguous run (padding targets scratch, excluded)
            self.ledger.record("splice", None,
                               pages=[int(p) for p in pages])
        args = (self._pools, jnp.asarray(page_idx), k, v, ks, vs)
        t0 = time.monotonic()
        with count_op_calls() as c:
            self._pools = self._splice_jit(*args)
        self._tl_add_ms("splice_ms", time.monotonic() - t0)
        self._record_programs("restore", c.count)
        if c.count:
            self._capture_cost("restore", self._splice_jit, args)

    def mesh_info(self) -> Optional[Dict[str, Any]]:
        """Mesh observability record (server stats / Prometheus):
        None when single-device, else axis sizes + device count."""
        if self.mesh is None:
            return None
        return {"axes": {str(a): int(self.mesh.shape[a])
                         for a in self.mesh.axis_names},
                "model_parallel": int(self.mesh.shape[self._mesh_axis]),
                "devices": int(self.mesh.size),
                "model_axis": self._mesh_axis}

    def _decode_body_fn(self):
        """The ONE single-token decode step body: shared verbatim by
        the per-token decode jit (``multi_step=1`` — byte-for-byte the
        pre-r19 trace) and by every iteration of the r19 multi-step
        macro program (models/gpt.py ``multi_step_decode``), so the
        two modes' per-step math is identical by construction — the
        bit-identity contract tests/test_multi_step_decode.py pins."""
        import jax

        from ..autograd.engine import no_grad
        from ..nn.decode import sample_token
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def step(state, pools, table, lens, tokens):
            caches = self._caches(pools, table, lens)
            # named_scope: metadata-only, UNCONDITIONAL (never keyed on
            # tracing state, so programs are identical tracing on/off)
            # — serving steps show up inside jax.profiler device traces
            with jax.named_scope("pt.decode_step"), self._head_ctx(), \
                    self._fuse_ctx(), \
                    bind_state(self.model, state), no_grad():
                hp = self._fused_head()
                if hp is not None:
                    # fused hot path (r13): hidden -> streaming lm_head
                    # argmax; the [B, vocab] logits never materialize
                    from ..nn.decode import fused_sample_token
                    hidden, nc = self.model.decode_hidden(
                        Tensor(tokens[:, None]), caches)
                    w, ty, bias = hp
                    nxt, _ = fused_sample_token(
                        raw(hidden)[:, -1], raw(w), 0.0,
                        transpose_y=ty,
                        bias=None if bias is None else raw(bias))
                else:
                    logits, nc = self.model.forward(
                        Tensor(tokens[:, None]), caches=caches)
                    # greedy serving mode through the ONE shared
                    # sampler (nn/decode.py) — the same call generate()
                    # and the speculative verify make
                    nxt, _ = sample_token(raw(logits)[:, -1], 0.0)
            new_pools = {
                "k": [raw(c.k_pages) for c in nc],
                "v": [raw(c.v_pages) for c in nc],
                "ks": [raw(c.k_scale) if self.kv_int8 else None
                       for c in nc],
                "vs": [raw(c.v_scale) if self.kv_int8 else None
                       for c in nc],
            }
            return nxt, self._constrain_pools(new_pools), \
                raw(nc[0].seq_lens)

        return step

    def _build_decode(self):
        import jax

        # donate the pools: the append scatters then update the pool
        # buffers IN PLACE instead of materializing a fresh copy of
        # every per-layer pool each token (~GBs/step at serving scale,
        # plus 2x peak KV memory); the engine always adopts the
        # returned pools, so the donated buffers are never reused.
        # (On CPU donation is ignored with a warning — harmless.)
        return jax.jit(self._decode_body_fn(), donate_argnums=(1,))

    def _build_multi_decode(self, has_chunk: bool = False):
        """The r19 macro program: up to ``multi_step`` iterations of
        the EXACT single-token decode body wrapped in one on-device
        early-exit loop (models/gpt.py ``multi_step_decode``), with
        the per-slot stop/mask bookkeeping the host used to run
        between launches carried in-program. ONE compile serves the
        engine lifetime (N is static; rem/eos/active are data).

        r22 (in-program inner loop): when ``_spec_inprogram`` the
        iteration body is the fused VERIFY step instead of the decode
        step — draft (device ngram/self twin), verify k+1 positions,
        and rewind via ``masked_run_advance`` carries, widening the
        token ring to [B, N, k+1]. When ``has_chunk`` the program also
        advances one half-prefilled slot's scheduled chained-prefill
        chunks, one per iteration, under a ``lax.cond``. Spec/chunk
        both off traces the byte-for-byte r19 program."""
        import jax
        import jax.numpy as jnp

        from ..models.gpt import multi_step_decode

        body = self._decode_body_fn()
        n = self.multi_step
        scratch = self._scratch
        spec_on = self._spec_inprogram
        if not spec_on and not has_chunk:
            def macro(state, pools, table, lens, tokens, active, rem,
                      eos):
                def step_fn(pl, tbl, ln, cur):
                    return body(state, pl, tbl, ln, cur)

                with jax.named_scope("pt.multi_step"):
                    return multi_step_decode(step_fn, pools, table,
                                             lens, tokens, active,
                                             rem, eos, n, scratch)

            return jax.jit(macro, donate_argnums=(1,))

        verify_body = self._verify_body_fn() if spec_on else None
        prefill_body = self._prefill_body_fn(True) if has_chunk else None
        dcfg = self._spec_device_draft
        k = int(self._spec_cfg.k) if spec_on else 0
        vocab = int(self.cfg.vocab_size)

        def macro(state, pools, table, lens, tokens, active, rem, eos,
                  *extra):
            from ..nn.decode import ngram_draft_tokens
            idx = 0
            spec = chunk = None
            if spec_on:
                hist, hist_len = extra[0], extra[1]
                idx = 2

                def draft_fn(h, hl, cur):
                    if dcfg["kind"] == "self":
                        return jnp.broadcast_to(
                            cur[:, None], (cur.shape[0], k))
                    return ngram_draft_tokens(
                        h, hl, k, dcfg["max_ngram"], dcfg["min_ngram"])

                def verify_fn(pl, tbl, ln, toks, valid):
                    key = jax.random.PRNGKey(0)  # greedy: unused
                    return verify_body(state, pl, tbl, ln, toks,
                                       valid, key)

                spec = {"k": k, "vocab": vocab, "draft_fn": draft_fn,
                        "verify_fn": verify_fn, "hist": hist,
                        "hist_len": hist_len}
            if has_chunk:
                (c_ids, c_valid, c_start, c_final, c_count,
                 c_slot) = extra[idx:idx + 6]

                def prefill_fn(pl, trow, slens, plen, ids):
                    return prefill_body(state, pl, trow, slens, plen,
                                        ids)

                chunk = {"prefill_fn": prefill_fn, "ids": c_ids,
                         "valid": c_valid, "start": c_start,
                         "final": c_final, "count": c_count,
                         "slot": c_slot}

            def step_fn(pl, tbl, ln, cur):
                return body(state, pl, tbl, ln, cur)

            with jax.named_scope("pt.multi_step_inner"):
                return multi_step_decode(step_fn, pools, table, lens,
                                         tokens, active, rem, eos, n,
                                         scratch, spec=spec,
                                         chunk=chunk)

        return jax.jit(macro, donate_argnums=(1,))

    def _prefill_body_fn(self, chained: bool):
        """The unjitted prefill body — ``_build_prefill`` wraps it in
        its own jit for boundary launches; the r22 macro builder
        embeds it in the while_loop body so a chained chunk advances
        INSIDE the macro program."""
        import jax

        from ..autograd.engine import no_grad
        from ..nn.decode import sample_token
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def prefill(state, pools, trow, slens, plen, ids):
            caches = self._caches(pools, trow, slens)
            with jax.named_scope(
                    "pt.prefill_chained" if chained else "pt.prefill"), \
                    self._head_ctx(), self._fuse_ctx(), \
                    bind_state(self.model, state), no_grad():
                hp = self._fused_head()
                if hp is not None:
                    # fused (r13): sample the first token straight from
                    # the last VALID hidden row — the [1, bucket, vocab]
                    # prefill logits tensor never materializes
                    from ..nn.decode import fused_sample_token
                    hidden, nc = self.model.decode_hidden(
                        Tensor(ids), caches, prefill_lens=plen,
                        prefill_chained=chained)
                    w, ty, bias = hp
                    nxt, _ = fused_sample_token(
                        raw(hidden)[:1, plen[0] - 1], raw(w), 0.0,
                        transpose_y=ty,
                        bias=None if bias is None else raw(bias))
                else:
                    logits, nc = self.model.forward(
                        Tensor(ids), caches=caches, prefill_lens=plen,
                        prefill_chained=chained)
                    nxt, _ = sample_token(raw(logits)[:1, plen[0] - 1],
                                          0.0)
            nxt = nxt[0]
            new_pools = {
                "k": [raw(c.k_pages) for c in nc],
                "v": [raw(c.v_pages) for c in nc],
                "ks": [raw(c.k_scale) if self.kv_int8 else None
                       for c in nc],
                "vs": [raw(c.v_scale) if self.kv_int8 else None
                       for c in nc],
            }
            return nxt, self._constrain_pools(new_pools)

        return prefill

    def _build_prefill(self, chained: bool):
        """One jitted prefill; jax.jit's shape-keyed cache compiles it
        once per prompt bucket (the bucket IS the ids shape). The
        ``chained`` variant starts from a non-empty slot (seq_lens =
        the prefix-cache hit length) and attends the stored prefix
        through the paged-attention reference (models/gpt.py
        prefill_chained); the fresh variant keeps the exact dense
        chunk-attention program the bit-identical tests pin."""
        import jax

        return jax.jit(self._prefill_body_fn(chained),
                       donate_argnums=(1,))

    def _get_prefill(self, chained: bool):
        if self._prefill_jits.get(chained) is None:
            self._prefill_jits[chained] = self._build_prefill(chained)
        return self._prefill_jits[chained]

    def _verify_body_fn(self):
        """The unjitted speculative-verify body — ``_build_verify``
        wraps it for boundary launches; the r22 macro builder embeds
        it as the while_loop iteration body when speculation runs
        in-program."""
        import jax

        from ..autograd.engine import no_grad
        from ..nn.decode import speculative_verify_tokens
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        temp = float(self._spec_cfg.temperature)
        tk = self._spec_cfg.top_k

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def verify(state, pools, table, lens, tokens, valid, key):
            caches = self._caches(pools, table, lens)
            with jax.named_scope("pt.verify_step"), self._head_ctx(), \
                    self._fuse_ctx(), \
                    bind_state(self.model, state), no_grad():
                hp = self._fused_head()
                if hp is not None:
                    # one-program fused verify (r13): the k+1-position
                    # scoring runs through the fused attention epilogue
                    # and the accept/resample decisions stream through
                    # the lm_head per position (nn/decode.py
                    # fused_verify_tokens) — draft scoring AND
                    # acceptance in the same fused program, with no
                    # [B, k+1, vocab] logits tensor on the greedy path
                    from ..nn.decode import fused_verify_tokens
                    hidden, nc = self.model.decode_hidden(
                        Tensor(tokens), caches, prefill_lens=valid,
                        prefill_chained=True)
                    w, ty, bias = hp
                    accept, resid, full, _ = fused_verify_tokens(
                        raw(hidden), tokens[:, 1:], raw(w), temp, tk,
                        key, transpose_y=ty,
                        bias=None if bias is None else raw(bias))
                else:
                    logits, nc = self.model.verify_step(Tensor(tokens),
                                                        caches, valid)
                    accept, resid, full, _ = speculative_verify_tokens(
                        raw(logits), tokens[:, 1:], temp, tk, key)
            new_pools = {
                "k": [raw(c.k_pages) for c in nc],
                "v": [raw(c.v_pages) for c in nc],
                "ks": [raw(c.k_scale) if self.kv_int8 else None
                       for c in nc],
                "vs": [raw(c.v_scale) if self.kv_int8 else None
                       for c in nc],
            }
            return accept, resid, full, self._constrain_pools(new_pools)

        return verify

    def _build_verify(self):
        """ONE jitted speculative verify step for the engine's whole
        lifetime (fixed [num_slots, k+1] shape): append the pending
        token + k drafts through the page tables (ragged per-slot
        valid counts park the tail on the scratch page), score all
        k+1 positions via models/gpt.py ``verify_step`` (the chained-
        prefill q_offsets paged-attention path), and compute the
        accept/resample decisions with nn/decode.py's shared sampler
        math. Lengths stay host-owned: the host rolls back past the
        longest accepted prefix, so rejected positions are simply
        never attended again."""
        import jax

        return jax.jit(self._verify_body_fn(), donate_argnums=(1,))

    def _unwind_prefill_failure(self, slot: int, req: DecodeRequest
                                ) -> None:
        """Shared unwind for a FAILED prefill launch — the whole
        prefill at admission or any chunk of a chunked prefill: free
        the pages and any speculative reservation, drop the
        prefix-cache pins, park the slot, and requeue at the head for
        a from-scratch retry — or FAIL typed once max_prefill_attempts
        accumulated, so a persistent fault can't wedge the queue head
        forever. A strict superset of what the whole-prefill path
        needs (its slot was never committed: lens/cur are still 0 and
        the _slots entry still None — re-clearing them is a no-op), so
        both leak-critical paths stay in sync by construction."""
        with self._led("prefill_unwind", req.req_id):
            self.allocator.free(req.req_id)
            if self._prefix_cache is not None and req.cache_keys:
                self._prefix_cache.release(req.cache_keys)
        req.cache_keys = ()
        req.prefill_done_len = 0
        self._table[slot] = self._scratch
        self._lens[slot] = 0
        self._cur[slot] = 0
        self._slots[slot] = None
        req.slot = None
        req.stats.prefill_attempts += 1
        if req.stats.prefill_attempts >= self.max_prefill_attempts:
            req.state = "failed"
            req.done = True
            req.stats.finish_t = time.monotonic()
            self._notify_complete(req)
        else:
            req.state = "queued"
            # a requeued request is queued again: close any stage span
            # (the chunked-mode "prefill") and reopen "queue" so the
            # tree mirrors the real lifecycle
            self._tr_end(req, state="prefill_failed")
            if req.trace is not None:
                req.span = req.trace.begin(
                    "queue", parent=req.trace.anchor,
                    retry=req.stats.prefill_attempts)
            self._queue.insert(0, req)

    def _check_pools_live(self, what: str) -> None:
        """Donated-buffer guard shared by every retrying jit call site
        (prefill, chunk prefill, verify): if an earlier attempt failed
        AFTER execution began, the donated pools are gone — a retry
        would feed the jit dead buffers. Surface a terminal
        (non-transient) error instead of a confusing backend one."""
        k0 = self._pools["k"][0]
        if getattr(k0, "is_deleted", None) is not None \
                and k0.is_deleted():
            raise RuntimeError(
                f"KV pool buffers were consumed by a failed donating "
                f"{what}; engine state is unrecoverable — rebuild "
                f"the engine")

    # -- scheduler ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.prompt_buckets[-1]

    def _fits(self, req: DecodeRequest) -> bool:
        """Could this request be admitted right now? Free pages plus
        whatever the prefix cache could evict — EXCLUDING the entries
        this request's own prefix match would pin (counting those as
        evictable made _fits optimistic: admission then pinned them,
        the allocation failed, and the scheduler charged phantom
        bypasses for an admission that never happened). ``match`` memoizes
        the chain hash on the request, so per-step fits checks cost
        dict lookups, not re-hashing the prompt."""
        capacity = len(req.prompt) + req.max_new_tokens
        need = -(-capacity // self.page_size)
        avail = self.allocator.free_count
        if self._prefix_cache is not None:
            keys, shared = self._prefix_cache.match(req.prompt, memo=req)
            need -= len(shared)
            avail += self._prefix_cache.evictable_pages(excluding=keys)
        if need <= avail and self.forecast_admission:
            # byte planning (r23): also charge the fleet's forecast
            # page burn over this request's expected lifetime. The
            # r18 EWMA over the step-timeline's free_pages deltas
            # gives pages/s; the horizon is how long this request
            # will realistically hold its pages (max_new_tokens at
            # the decode EMA). A positive burn rate shrinks avail by
            # the pages the ALREADY-ADMITTED load will take in that
            # window — landing a request the instant books accept but
            # the forecast cannot carry is how pools thrash.
            from .page_ledger import forecast_exhaustion
            fc = forecast_exhaustion(self.step_timeline())
            rate = fc.get("rate_pages_per_s")
            if rate is not None and rate > 0 and \
                    self.decode_ema_s is not None:
                horizon_s = req.max_new_tokens * self.decode_ema_s
                burn = int(rate * horizon_s)
                if need > avail - burn:
                    self.forecast_denials += 1
                    return False
        return need <= avail

    def _partial_debt_by_class(self) -> Dict[int, int]:
        """In-flight prefill debt (un-stored suffix tokens of admitted
        half-prefilled slots) per priority class — the chunk-budget
        admission gate's input."""
        out: Dict[int, int] = {}
        for r in self._slots:
            if r is not None and r.state == "prefill_partial":
                rem = len(r.prompt) - r.prefill_done_len
                out[r.priority] = out.get(r.priority, 0) + rem
        return out

    def _debt_allows(self, req: DecodeRequest) -> bool:
        """Per-class prefill-debt admission gate (chunked mode with an
        SLO scheduler carrying ``max_prefill_debt_tokens``): don't turn
        every slot into half-prefilled work of one class — a stream of
        long BATCH prompts is admitted only while the class's in-flight
        debt stays under the cap. A class with ZERO in-flight debt is
        always admissible (the cap bounds concurrency, it must never
        lock a class out entirely)."""
        if self.prefill_chunk_tokens is None:
            return True
        cfg = getattr(self._scheduler, "cfg", None)
        cap = getattr(cfg, "max_prefill_debt_tokens", None)
        if cap is None:
            return True
        cur = self._partial_debt_by_class().get(req.priority, 0)
        if cur == 0:
            return True
        add = len(req.prompt)
        if self._prefix_cache is not None:
            _keys, shared = self._prefix_cache.match(req.prompt,
                                                     memo=req)
            add -= len(shared) * self.page_size
        return cur + add <= cap

    def _admissible(self, req: DecodeRequest) -> bool:
        return self._fits(req) and self._debt_allows(req)

    def _select_next(self) -> Optional[DecodeRequest]:
        if not self._queue:
            return None
        if self._scheduler is not None:
            idx = self._scheduler.select(self._queue, self._admissible,
                                         time.monotonic())
            return self._queue.pop(idx) if idx is not None else None
        # built-in FIFO: head or nothing (don't starve the head)
        if self._admissible(self._queue[0]):
            return self._queue.pop(0)
        return None

    def _shed_overloaded(self) -> List[DecodeRequest]:
        """Let the scheduler shed queued requests past their SLO (the
        typed-overload path); returns what was shed so callers (the
        server) can answer those clients."""
        if self._scheduler is None or not self._queue:
            return []
        doomed = self._scheduler.shed(self._queue, time.monotonic())
        now = time.monotonic()
        for req in doomed:
            self._queue.remove(req)
            req.state = "shed"
            req.done = True
            req.stats.finish_t = now
            self._notify_complete(req)
        return doomed

    def set_on_complete(self, fn: Optional[Callable[["DecodeRequest"],
                                                    None]]) -> None:
        """Swap the completion hook (e.g. attach metrics only after a
        warm-up batch so compile time doesn't pollute TTFT)."""
        self._on_complete = fn

    def _notify_complete(self, req: DecodeRequest) -> None:
        # multi-step decode (r19): a request terminating at a macro
        # boundary may still hold undelivered ring tokens — stream
        # them FIRST so tokens always precede the completion, on
        # every terminal path (no-op outside multi-step mode)
        self._flush_req_emissions(req)
        tr = req.trace
        if tr is not None:
            # EVERY terminal path funnels through here, so this is the
            # one place open stage spans close and the tree finishes —
            # the zero-leaked-open-spans contract. Resurrection
            # detaches req.trace BEFORE teardown, so a replayed
            # request's tree survives to be continued, not finished.
            self._tr_end(req, state=req.state)
            tr.event("complete", parent=tr.anchor, state=req.state,
                     tokens_out=len(req.generated),
                     req_id=req.req_id)
            tr._tracer.finish(tr, state=req.state)
        if self._on_complete is not None:
            self._on_complete(req)

    def _emit_token(self, req: DecodeRequest, tok: int) -> None:
        # fires BEFORE _maybe_finish so streamed tokens always precede
        # the completion notification; callbacks run on the engine
        # thread and must not raise — the server's callback catches
        # its own socket errors
        if self.multi_step > 1 and (self._spec_cfg is None
                                    or self._spec_inprogram):
            # multi-step mode (r19): EVERY emission rides the pending
            # queue — boundary-time prefill first-tokens included —
            # so the stream keeps (step, slot) order: the drained
            # ring's tokens (earlier steps) always precede this
            # boundary's admissions, and per-request streams match
            # multi_step=1 exactly (cross-request interleave matches
            # too whenever admission lands at the same points; the
            # boundary-coarsened admission CADENCE is the one thing N
            # changes). _deliver_pending streams the queue after the
            # next launch is dispatched; terminal paths flush a
            # request's share first (_notify_complete).
            self._pending_emit.append((req, tok, self._finish_due(req)))
            return
        req.last_emit_t = time.monotonic()
        if req.on_token is not None:
            req.on_token(req.req_id, tok, self._finish_due(req))

    # -- typed mid-flight eviction (deadline / stall / replay) -------------

    def _evict_slot(self, slot: int, state: str) -> DecodeRequest:
        """Tear one active slot down with a typed terminal ``state``:
        return its pages AND any outstanding speculative reservation
        (`PageAllocator.free` drops both — the same unwinding the
        rejection-rollback machinery relies on), drop the prefix-cache
        pins, park the slot on the scratch page, and notify."""
        req = self._slots[slot]
        self._account_req_pages(req)
        if self.ledger is not None and state in ("stalled", "deadline"):
            # the stall/deadline unwind forensics (r18): snapshot the
            # pages' event history BEFORE the free below rewrites it —
            # the server's stall flight bundle and typed reply carry it
            req.page_forensics = self.ledger.history_for_owner(
                req.req_id)
        with self._led(state, req.req_id):
            self.allocator.free(req.req_id)
            if self._prefix_cache is not None and req.cache_keys:
                # for a half-prefilled slot these are the matched chain
                # pins acquired at admission (insert() never ran); for a
                # decoding slot, the full inserted chain — release() is
                # the right unwind for both
                self._prefix_cache.release(req.cache_keys)
                req.cache_keys = ()
        req.prefill_done_len = 0
        req.state = state
        req.done = True
        req.stats.finish_t = time.monotonic()
        req.stats.tokens_out = len(req.generated)
        self._table[slot] = self._scratch
        self._lens[slot] = 0
        self._cur[slot] = 0
        self._slots[slot] = None
        self._notify_complete(req)
        return req

    def _terminate_queued(self, req: DecodeRequest, state: str) -> None:
        self._queue.remove(req)
        req.state = state
        req.done = True
        req.stats.finish_t = time.monotonic()
        self._notify_complete(req)

    def _deadline_hopeless(self, req: DecodeRequest, now: float) -> bool:
        """Admission gate: True when the request provably cannot finish
        before its deadline — already expired, or even the BEST-case
        remaining work times the observed step cadence overshoots it.
        Best-case, not expected: ``max_new_tokens`` is a cap (an
        ``eos_token`` can legally end the generation after one token)
        and a speculative step emits up to k+1 tokens — overestimating
        here would shed feasible work. Without an EMA yet (cold engine)
        only hard expiry counts: guessing would shed work a fast engine
        could still serve.

        Chunked mode additionally counts the queued prompt's REMAINING
        prefill chunks (after its actual memoized prefix-cache match)
        at the per-chunk EMA — sound because the fixed chunk bucket
        makes every chunk the same compiled program, so its cost is a
        constant the EMA tracks, unlike whole prefills whose cost
        scales with prompt length (which is why the unchunked gate
        never charged prefill time at all)."""
        if req.deadline_t is None:
            return False
        if now >= req.deadline_t:
            return True
        if self.decode_ema_s is not None:
            need = 1 if req.eos_token is not None else req.max_new_tokens
            # decode_ema_s is per LAUNCH: one token for the per-token
            # engine, up to k+1 for a speculative verify, up to
            # multi_step for a macro launch (r19 — the EMA is tracked
            # per macro at drain, so the per-token estimate is ema/N
            # and charging ema per token would shed feasible work)
            if self._spec_cfg is not None:
                per_step = self._spec_cfg.k + 1
                if self._spec_inprogram:
                    # r22: one macro launch carries up to N verify
                    # iterations, each emitting up to k+1 tokens
                    per_step *= self.multi_step
            else:
                per_step = self.multi_step
            steps = -(-need // per_step)
            est = steps * self.decode_ema_s
            if self.prefill_chunk_tokens is not None:
                cached = 0
                if self._prefix_cache is not None:
                    _keys, shared = self._prefix_cache.match(req.prompt,
                                                             memo=req)
                    cached = len(shared) * self.page_size
                chunks = -(-(len(req.prompt) - cached)
                           // self.prefill_chunk_tokens)
                if self._chunk_inprogram:
                    # r22 in-program units: chained chunks ride macro
                    # launches (up to N per launch), so a queued
                    # prompt's best case is ceil(chunks/N) whole
                    # launches at the per-LAUNCH decode EMA — not
                    # per-chunk boundary wall time
                    est += (-(-chunks // self.multi_step)
                            * self.decode_ema_s)
                elif self.prefill_chunk_ema_s is not None:
                    est += chunks * self.prefill_chunk_ema_s
            return now + est > req.deadline_t
        return False

    def expire_deadlines(self, now: Optional[float] = None
                         ) -> List[DecodeRequest]:
        """Terminate everything past its deadline with the typed
        "deadline" state: queued requests are shed before prefill,
        active slots are evicted mid-flight with their pages (and any
        speculative reservation) returned. Runs at the top of every
        step and is safe to call from the serving loop even when the
        step itself is failing (host state only). Multi-step engines
        flush the in-flight launch first — never sweep stale slot
        state, and deliver its tokens/completions so a failing step
        loop can't strand answered work (r19)."""
        self._flush_macro()
        return self._expire_deadlines_inner(now)

    def _expire_deadlines_inner(self, now: Optional[float] = None
                                ) -> List[DecodeRequest]:
        now = time.monotonic() if now is None else now
        expired: List[DecodeRequest] = []
        for req in [r for r in self._queue
                    if r.deadline_t is not None and now >= r.deadline_t]:
            self._terminate_queued(req, "deadline")
            expired.append(req)
        for slot, req in enumerate(self._slots):
            if req is not None and req.deadline_t is not None \
                    and now >= req.deadline_t:
                expired.append(self._evict_slot(slot, "deadline"))
        return expired

    def evict_stalled(self, now: Optional[float] = None
                      ) -> List[DecodeRequest]:
        """Stall watchdog: evict active slots that have delivered no
        token for ``stall_timeout_s`` with the typed "stalled" state
        instead of holding pages forever. No-op when the watchdog is
        off. Like `expire_deadlines` this touches host state only, so
        the serving loop calls it even mid engine failure."""
        if self.stall_timeout_s is None:
            return []
        self._flush_macro()
        return self._evict_stalled_inner(now)

    def _evict_stalled_inner(self, now: Optional[float] = None
                             ) -> List[DecodeRequest]:
        if self.stall_timeout_s is None:
            return []
        now = time.monotonic() if now is None else now
        out: List[DecodeRequest] = []
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            last = max(req.last_emit_t, req.stats.admit_t)
            if self.multi_step > 1 and req.state == "decoding":
                # multi-step mode delivers tokens once per macro
                # boundary, not per step — engine-wide drain progress
                # is the liveness signal (every decoding slot gets
                # tokens each healthy launch; a broken engine stops
                # draining anywhere and the timestamp goes stale, so
                # a genuine stall still fires typed). Same shape as
                # the chunked-prefill _last_chunk_t rule below.
                last = max(last, self._last_macro_t)
            if req.state == "prefill_partial":
                # a half-prefilled slot may be healthily WAITING its
                # turn for the single per-step chunk budget while
                # another slot's chunks land — engine-wide chunk
                # progress is its liveness signal. A broken step stops
                # landing chunks ANYWHERE, so the timestamp goes stale
                # and the waiting slot still stalls out typed.
                last = max(last, self._last_chunk_t)
            if now - last > self.stall_timeout_s:
                out.append(self._evict_slot(slot, "stalled"))
        return out

    def dump_inflight(self) -> List[DecodeRequest]:
        """Snapshot every request the engine still owes an answer for
        (active slots + wait queue) in submission order — the engine-
        resurrection input: each request's prompt plus already-emitted
        tokens is everything needed to rebuild its KV state on a fresh
        engine via a chained greedy prefill (bit-identical continuation
        is the paged design's recovery dividend). Does NOT release
        anything; callers tear down via close()."""
        # multi-step (r19): fold any in-flight launch's tokens into
        # the snapshot first — those tokens were NEVER delivered (the
        # ring streams at the NEXT boundary), so on a failed drain
        # the pre-launch state is equally gapless to replay from
        try:
            self._flush_macro()
        except Exception:
            # the in-flight computation died with the engine; its
            # tokens were never generated as far as any client knows
            # (earlier drains' emissions still deliver)
            self._pending_macro = None
            self._deliver_pending()
        live = [r for r in self._slots if r is not None]
        return sorted(live + list(self._queue), key=lambda r: r.req_id)

    def _admit(self) -> None:
        if self.pause_admission:
            # swap drain gate (r24): hold the queue — a request
            # admitted now would pin active slots and starve the
            # pending weight swap of its num_active == 0 window
            return
        self._shed_overloaded()
        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                continue
            while True:
                req = self._select_next()
                if req is None:
                    return
                if self._deadline_hopeless(req, time.monotonic()):
                    # never admit a request that can't finish: prefill
                    # compute spent on it is pure waste and its pages
                    # would be clawed back next step anyway
                    req.state = "deadline"
                    req.done = True
                    req.stats.finish_t = time.monotonic()
                    self._notify_complete(req)
                    continue
                break
            committed = self._admit_into(slot, req)
            if committed is False:
                return
            if committed is None:
                # deadline expired mid-prefill: the admission was
                # unwound typed and the slot is free again. No queue
                # jump happened, so fall through WITHOUT the fairness
                # charge — phantom bypass charges from a stream of
                # deadline-tight requests could otherwise starve the
                # queue (note_admitted is for COMMITTED admissions
                # only). The next step's _admit refills the slot.
                continue
            # fairness accounting happens only on COMMITTED admissions
            # (a failed/unwound admission must not charge bypasses)
            note = getattr(self._scheduler, "note_admitted", None)
            if note is not None:
                note(req, self._queue, time.monotonic())

    def _admit_into(self, slot: int, req: DecodeRequest
                    ) -> Optional[bool]:
        """Admit ``req`` into ``slot``. Returns True on a committed
        admission, False when it doesn't fit (stop admitting this
        step), None when the deadline expired mid-prefill and the
        admission was unwound typed (slot is free again; caller must
        not charge fairness accounting)."""
        jnp = self._jnp
        cache = self._prefix_cache
        tr = req.trace
        sp_admit = (tr.begin("admit", parent=tr.anchor, slot=slot)
                    if tr is not None else None)
        keys: Tuple[Hashable, ...] = ()
        shared: List[int] = []
        if cache is not None:
            keys, shared = cache.match(req.prompt, memo=req)
            # a device hit is a device hit; the DISTINCTION from
            # restored pages matters for the per-tier counters, so
            # remember where the device chain ended (insert() and the
            # stats below use it)
            req._pfx_device_hits = len(keys)
            # pin the matched chain BEFORE restore/allocation: both
            # the restore's own eviction pressure and the fallback
            # below must never reclaim pages we are about to point
            # this slot's table row at
            cache.acquire(keys)
            if getattr(cache, "spill_enabled", False):
                # hierarchical tiers (r15): extend the device match by
                # restoring spilled blobs into fresh pages (device_put
                # + page-table splice) — each restored page is one
                # prefix page this request does NOT re-prefill. A tier
                # miss mid-chain just stops here; the chained-prefill
                # suffix path below covers the rest, so outputs are
                # bit-identical either way.
                rsp = (tr.begin("restore", parent=sp_admit)
                       if tr is not None else None)
                with self._led("restore", req.req_id):
                    rkeys, rpages, rinfo = cache.restore_from_spill(
                        req.prompt, keys, self.allocator, memo=req)
                if rkeys and self.ledger is not None:
                    self.ledger.record("restore", req.req_id,
                                       pages=rpages)
                if tr is not None:
                    # fetched-vs-restored split (r20): how many of the
                    # restored pages arrived over the wire vs from a
                    # local eviction's blob
                    tr.end(rsp, pages=len(rkeys),
                           fetched=rinfo.get("fetched", 0),
                           corrupt=rinfo.get("corrupt", 0))
                if rkeys:
                    cache.acquire(rkeys)
                    keys = tuple(keys) + rkeys
                    shared = list(shared) + rpages
                if rkeys or rinfo.get("corrupt"):
                    st = req.stats
                    st.restored_pages += len(rkeys)
                    st.restored_host_pages += rinfo.get("host", 0)
                    st.restored_disk_pages += rinfo.get("disk", 0)
                    st.restore_corrupt += rinfo.get("corrupt", 0)
                    st.restore_ms += rinfo.get("ms", 0.0)
                    st.handoff_pages += rinfo.get("fetched", 0)
        cached_len = len(shared) * self.page_size
        capacity = len(req.prompt) + req.max_new_tokens
        need = -(-capacity // self.page_size)
        private_need = need - len(shared)

        def grab():
            if not self._reserve_growth:
                return self.allocator.alloc(req.req_id, private_need)
            # speculative AND multi-step modes bind only the
            # prefill-covering pages and RESERVE the rest of the
            # capacity: decode grows the page set on demand
            # (_ensure_pages — per spec step, or per macro launch to
            # cover the next min(N, rem) positions) and speculative
            # rollback returns wholly-unused pages (_rollback_pages)
            # without ever risking a mid-decode allocation failure
            prefill_need = (-(-len(req.prompt) // self.page_size)
                            - len(shared))
            if not self.allocator.reserve(req.req_id, private_need):
                return None
            return self.allocator.alloc_reserved(req.req_id,
                                                 prefill_need)

        from ..distributed.fault_inject import InjectedFault
        try:
            with self._led("admit", req.req_id):
                pages = grab()
                if pages is None and cache is not None:
                    if cache.evict_until(self.allocator, private_need):
                        pages = grab()
        except InjectedFault:
            # armed alloc.page site: a transient allocation failure is
            # the same outcome as not fitting — unwind and requeue;
            # the next step retries admission (alloc/reserve raise
            # BEFORE mutating the free list, so there is nothing to
            # roll back)
            pages = None
        if pages is None:
            if tr is not None:
                tr.end(sp_admit, admitted=False, reason="no_fit")
            if cache is not None:
                cache.release(keys)
            self._queue.insert(0, req)
            return False
        req.stats.admit_t = time.monotonic()
        # page-attribution baseline (r18): peak starts at the admitted
        # holding, page-seconds integrate from here
        self._account_req_pages(req, req.stats.admit_t)
        if tr is not None:
            # the queue stage ends at the committed admission; the
            # scheduler's explain() (duck-typed) attributes WHY the
            # request waited (class, promotion, bypasses)
            exp = {}
            explain = getattr(self._scheduler, "explain", None)
            if explain is not None:
                try:
                    exp = dict(explain(req, req.stats.admit_t))
                except Exception:
                    exp = {}
            self._tr_end(req, bypass_count=req.bypass_count, **exp)
        req.stats.cached_pages = len(shared)
        req.stats.cached_tokens = cached_len
        req.stats.prompt_pages = (len(req.prompt) - 1) // self.page_size
        req.stats.cache_enabled = cache is not None
        req.cache_keys = keys
        req.state = "prefill"
        row = np.full((self.max_pages,), self._scratch, np.int32)
        row[:len(shared)] = shared
        row[len(shared):len(shared) + len(pages)] = pages
        self._table[slot] = row
        if self.prefill_chunk_tokens is not None:
            # chunked admission (r11): bind the pages, store NOTHING
            # yet — the suffix is enqueued as page-aligned chunks that
            # _advance_prefill_chunk trickles in across decode steps.
            # The slot's stored length is exactly the prefix-cache hit
            # (shared pages already hold valid KV); matched cache pins
            # stay on req.cache_keys so every eviction path releases
            # them, and insert() runs only when the LAST chunk lands.
            req.state = "prefill_partial"
            req.prefill_done_len = cached_len
            req.slot = slot
            self._lens[slot] = cached_len
            self._cur[slot] = 0
            self._slots[slot] = req
            if tr is not None:
                tr.end(sp_admit, cached_pages=len(shared),
                       restored_pages=req.stats.restored_pages)
                # chunked mode: the prefill STAGE stays open across
                # chunks; each chunk appends a child span
                req.span = tr.begin(
                    "prefill", parent=tr.anchor, chunked=True,
                    remaining=len(req.prompt) - cached_len)
            return True
        suffix = req.prompt[cached_len:]
        bucket = self._bucket(len(suffix))
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :len(suffix)] = suffix
        chained = cached_len > 0
        jit = self._get_prefill(chained)
        if tr is not None:
            tr.end(sp_admit, cached_pages=len(shared),
                   restored_pages=req.stats.restored_pages)
        sp_pref = (tr.begin("prefill", parent=tr.anchor, bucket=bucket,
                            chained=chained)
                   if tr is not None else None)

        def run_prefill():
            from ..dispatch import count_op_calls
            from ..distributed.fault_inject import fault_point
            self._check_pools_live("prefill")
            fault_point("serving.prefill")
            kind = "prefill_chained" if chained else "prefill"
            args = (self._fresh_state(refresh=True), self._pools,
                    jnp.asarray(row[None]),
                    jnp.asarray([cached_len], jnp.int32),
                    jnp.asarray([len(suffix)], jnp.int32),
                    jnp.asarray(ids))
            with count_op_calls() as c:
                out = jit(*args)
            self._record_programs(kind, c.count)
            if c.count:
                self._capture_cost(kind, jit, args)
            return out

        t0 = time.monotonic()
        try:
            if self._prefill_retry is not None:
                nxt, pools = self._prefill_retry.call(
                    run_prefill, site="serving.prefill")
            else:
                nxt, pools = run_prefill()
        except Exception:
            # unwind the half-applied admission so a prefill failure
            # (e.g. a remote-compile transport error on a new prompt
            # bucket, or an exhausted serving.prefill retry) is
            # retryable instead of losing the request and leaking its
            # pages, then surface the error. (If the failure hit AFTER
            # execution began, the donated pool buffers may be gone
            # with it — compile-time failures, the documented class,
            # leave them untouched.)
            if tr is not None:
                tr.end(sp_pref, error=True)
            self._unwind_prefill_failure(slot, req)
            raise
        self._pools = pools
        now = time.monotonic()
        req.stats.prefill_ms = (now - t0) * 1e3
        self._tl_add_ms("prefill_ms", now - t0)
        if tr is not None:
            tr.end(sp_pref, ms=round(req.stats.prefill_ms, 3))
        req.stats.prefill_attempts += 1
        req.stats.prefill_chunks = 1  # whole prefill = one launch
        if req.deadline_t is not None and now >= req.deadline_t:
            # deadline expired MID-PREFILL: the forward pass is paid
            # for, but delivering a token past the deadline breaks the
            # contract — unwind the admission typed instead (pools were
            # adopted above, so device state stays coherent)
            self._account_req_pages(req, now)
            if self.ledger is not None:
                # same forensics contract as _evict_slot's deadline
                # path: snapshot the page history BEFORE free rewrites
                # it so the typed reply can carry it
                req.page_forensics = self.ledger.history_for_owner(
                    req.req_id)
            with self._led("deadline", req.req_id):
                self.allocator.free(req.req_id)
                if cache is not None:
                    cache.release(keys)
                    req.cache_keys = ()
            self._table[slot] = self._scratch
            req.state = "deadline"
            req.done = True
            req.stats.finish_t = now
            self._notify_complete(req)
            return None
        req.stats.first_token_t = now
        self._lens[slot] = len(req.prompt)
        self._cur[slot] = int(nxt)
        req.slot = slot
        req.state = "decoding"
        req.generated.append(int(nxt))
        req.stats.tokens_out = 1
        if cache is not None:
            # the slot's full prompt pages now hold valid KV — hand
            # them to the cache (ownership transfer, refcount held by
            # this request until it finishes)
            req.cache_keys = cache.insert(
                req.prompt, row, self.allocator, req.req_id,
                self.page_size, keys,
                device_hits=getattr(req, "_pfx_device_hits", None))
        self._slots[slot] = req
        if tr is not None:
            tr.event("first_token", parent=tr.anchor, token=int(nxt))
            req.span = tr.begin("decode", parent=tr.anchor)
        self._emit_token(req, int(nxt))
        self._maybe_finish(slot)
        return True

    # -- chunked prefill (r11) ---------------------------------------------

    def _select_chunk_slot(self, partial: List[Tuple[int, DecodeRequest]]
                           ) -> Optional[int]:
        """Which half-prefilled slot gets this step's chunk budget.
        With a scheduler exposing ``select_chunk`` (serving/
        scheduler.py's chunk-budget policy: INTERACTIVE decode preempts
        lower-class prefill chunks, bounded deferrals), defer to it;
        the built-in policy advances the oldest admission (FIFO by
        req_id). When nothing is decoding there is nothing to preempt,
        so the scheduler contract requires a pick — the engine would
        otherwise spin without progress."""
        sel = getattr(self._scheduler, "select_chunk", None)
        if sel is not None:
            decoding = [r for r in self._slots
                        if r is not None and r.state == "decoding"]
            return sel(partial, decoding, time.monotonic())
        return min(partial, key=lambda sr: sr[1].req_id)[0]

    def _advance_prefill_chunk(self, slot: Optional[int] = None) -> bool:
        """Spend this step's prefill budget: advance AT MOST ONE
        half-prefilled slot by one page-aligned chunk of
        ``prefill_chunk_tokens`` tokens through the chained-prefill jit
        (``cached_len`` = tokens stored so far — shared prefix pages
        and prior chunks are the same "already stored" case, so the
        chunk attends everything before it through the paged-attention
        q_offsets path). The chunk ids are ALWAYS padded to the one
        fixed chunk bucket, so the engine pays one prefill compile per
        chained-ness, not one per suffix length. The final chunk's
        logits produce the first generated token, exactly like a whole
        prefill. Returns True when a chunk ran.

        ``slot``: pre-selected target (the r22 in-program planner
        already ran the scheduler's pick and routes the dense FRESH
        first chunk back here) — skips re-selection so the
        chunk-budget policy is consulted exactly once per boundary."""
        partial = [(i, r) for i, r in enumerate(self._slots)
                   if r is not None and r.state == "prefill_partial"]
        if not partial:
            return False
        if slot is None:
            slot = self._select_chunk_slot(partial)
        if slot is None:
            return False  # scheduler deferred: decode preempts
        jnp = self._jnp
        req = self._slots[slot]
        cache = self._prefix_cache
        chunk = self.prefill_chunk_tokens
        done = req.prefill_done_len
        suffix = req.prompt[done:done + chunk]
        final = done + len(suffix) == len(req.prompt)
        ids = np.zeros((1, chunk), np.int32)
        ids[0, :len(suffix)] = suffix
        # chunk 1 of an uncached prompt keeps the exact dense fresh-
        # prefill program (chained=False), so a suffix that fits in one
        # chunk is byte-for-byte the whole-prefill admission
        chained = done > 0
        jit = self._get_prefill(chained)
        row = self._table[slot]

        def run_chunk():
            from ..dispatch import count_op_calls
            from ..distributed.fault_inject import fault_point
            self._check_pools_live("prefill")
            fault_point("serving.prefill")
            kind = "prefill_chained" if chained else "prefill"
            args = (self._fresh_state(refresh=True), self._pools,
                    jnp.asarray(row[None]),
                    jnp.asarray([done], jnp.int32),
                    jnp.asarray([len(suffix)], jnp.int32),
                    jnp.asarray(ids))
            with count_op_calls() as c:
                out = jit(*args)
            self._record_programs(kind, c.count)
            if c.count:
                self._capture_cost(kind, jit, args)
            return out

        tr = req.trace
        sp_chunk = (tr.begin("prefill_chunk", parent=req.span,
                             idx=req.stats.prefill_chunks,
                             done_tokens=done)
                    if tr is not None else None)
        t0 = time.monotonic()
        try:
            if self._prefill_retry is not None:
                nxt, pools = self._prefill_retry.call(
                    run_chunk, site="serving.prefill")
            else:
                nxt, pools = run_chunk()
        except Exception:
            # unwind the WHOLE half-prefilled admission (not just this
            # chunk) — shared with the whole-prefill failure path
            if tr is not None:
                tr.end(sp_chunk, error=True)
            self._unwind_prefill_failure(slot, req)
            raise
        self._pools = pools
        now = time.monotonic()
        self._tl_add_ms("chunk_ms", now - t0)
        if tr is not None:
            tr.end(sp_chunk, tokens=len(suffix))
        req.stats.prefill_ms += (now - t0) * 1e3
        req.stats.prefill_chunks += 1
        if self._chunk_warm[chained]:
            dt = now - t0
            self.prefill_chunk_ema_s = dt \
                if self.prefill_chunk_ema_s is None \
                else 0.8 * self.prefill_chunk_ema_s + 0.2 * dt
        else:
            # first launch of this variant: compile-dominated, skip
            self._chunk_warm[chained] = True
        req.prefill_done_len = done + len(suffix)
        self._lens[slot] = req.prefill_done_len
        # chunk progress is liveness for the stall watchdog: a long
        # prompt legitimately emits nothing while its chunks land, but
        # a slot whose chunks stopped landing (step failures) still
        # stalls out and is evicted typed. The engine-wide timestamp
        # additionally protects OTHER half-prefilled slots waiting
        # their turn for the per-step chunk budget.
        req.last_emit_t = now
        self._last_chunk_t = now
        req.chunk_deferrals = 0
        if req.deadline_t is not None and now >= req.deadline_t:
            # expired mid-prefill: the chunk is paid for, but delivering
            # a token past the deadline breaks the contract — evict
            # typed (pages, reservations and cache pins all return)
            self._evict_slot(slot, "deadline")
            return True
        if not final:
            return True
        # last chunk: its logits ARE the whole prefill's logits — emit
        # the first token and promote the slot to the decode batch
        req.stats.prefill_attempts += 1
        req.stats.first_token_t = now
        self._cur[slot] = int(nxt)
        req.state = "decoding"
        req.generated.append(int(nxt))
        req.stats.tokens_out = 1
        if tr is not None:
            # close the chunked "prefill" stage, mark the first token,
            # and open the decode stage — same shape as whole prefill
            self._tr_end(req, chunks=req.stats.prefill_chunks)
            tr.event("first_token", parent=tr.anchor, token=int(nxt))
            req.span = tr.begin("decode", parent=tr.anchor)
        if cache is not None:
            # the slot's full prompt pages now hold valid KV — hand
            # them to the cache (ownership transfer; the matched keys
            # from admission are the already-acquired chain head)
            req.cache_keys = cache.insert(
                req.prompt, row, self.allocator, req.req_id,
                self.page_size, req.cache_keys,
                device_hits=getattr(req, "_pfx_device_hits", None))
        self._emit_token(req, int(nxt))
        self._maybe_finish(slot)
        return True

    def _plan_inprogram_chunks(self) -> Optional[Dict[str, Any]]:
        """r22: schedule up to ``multi_step`` CHAINED prefill chunks of
        one half-prefilled slot as per-iteration work INSIDE the next
        macro launch. Consults the same chunk-budget policy as the
        boundary path (one scheduler pick per boundary), then builds
        the chunk arrays the macro program indexes per iteration:
        iteration j runs chunk j while the other slots decode/verify —
        the launch never stalls for the prefill, which is the r22
        answer to the N-vs-TTFT trade.

        The dense FRESH first chunk of an uncached prompt stays at the
        boundary (routed back through ``_advance_prefill_chunk``): the
        bit-identical pins fix chunk 1 to the exact dense prefill
        program, and it is also each prompt's only non-chained chunk.
        Returns the plan dict (``None``: nothing to do this launch)."""
        partial = [(i, r) for i, r in enumerate(self._slots)
                   if r is not None and r.state == "prefill_partial"]
        if not partial:
            return None
        slot = self._select_chunk_slot(partial)
        if slot is None:
            return None  # scheduler deferred: decode preempts
        req = self._slots[slot]
        if req.prefill_done_len == 0:
            self._advance_prefill_chunk(slot=slot)
            return None
        n = self.multi_step
        chunk = self.prefill_chunk_tokens
        done = req.prefill_done_len
        total = len(req.prompt)
        count = min(n, -(-(total - done) // chunk))
        ids = np.zeros((n, chunk), np.int32)
        valid = np.zeros((n,), np.int32)
        start = np.zeros((n,), np.int32)
        final = np.zeros((n,), bool)
        pos = done
        for j in range(count):
            suffix = req.prompt[pos:pos + chunk]
            ids[j, :len(suffix)] = suffix
            valid[j] = len(suffix)
            start[j] = pos
            pos += len(suffix)
            final[j] = pos == total
        return {"slot": slot, "req": req, "count": count,
                "done0": done, "end": pos, "tokens": pos - done,
                "has_final": bool(final[:count].any()),
                "final_idx": int(np.argmax(final)) if final.any() else -1,
                "ids": ids, "valid": valid, "start": start,
                "final": final}

    def _finish_due(self, req: DecodeRequest) -> bool:
        hit_eos = (req.eos_token is not None and req.generated and
                   req.generated[-1] == req.eos_token)
        return len(req.generated) >= req.max_new_tokens or hit_eos

    def _maybe_finish(self, slot: int, notify: bool = True) -> None:
        req = self._slots[slot]
        if req is None:
            return
        if self._finish_due(req):
            self._finish_slot(slot, notify=notify)

    def _finish_slot(self, slot: int, notify: bool = True) -> None:
        """Terminal "done" teardown for one slot: free pages, release
        cache pins, park on scratch. ``notify=False`` (the macro-drain
        path, r19) defers _notify_complete to the delivery phase so
        the request's ring tokens stream before its completion —
        delivery calls _notify_complete after the last token."""
        req = self._slots[slot]
        req.done = True
        req.state = "done"
        req.stats.finish_t = time.monotonic()
        req.stats.tokens_out = len(req.generated)
        self._finished[req.req_id] = req
        self._account_req_pages(req)
        with self._led("done", req.req_id):
            self.allocator.free(req.req_id)
            if self._prefix_cache is not None and req.cache_keys:
                self._prefix_cache.release(req.cache_keys)
                req.cache_keys = ()
        self._table[slot] = self._scratch  # park on scratch page
        self._lens[slot] = 0
        self._cur[slot] = 0
        self._slots[slot] = None
        if notify:
            self._notify_complete(req)

    # -- device-resident multi-step decode (r19) ----------------------------
    #
    # multi_step=N turns the per-token launch cadence into one macro
    # launch per N tokens: _dispatch_macro pre-binds each decoding
    # slot's growth pages out of its admission reservation and fires
    # the on-device while_loop program (models/gpt.py
    # multi_step_decode); JAX async dispatch returns immediately, so
    # the boundary that DRAINS launch K runs at the top of step K+1 —
    # the host spends launch K's device time delivering ring K−1's
    # tokens (on_token/tracing/metrics) and on the serving loop's
    # inbox/socket work. Admission and chunked prefill run at the
    # boundary itself, in the drain->dispatch gap: they rewrite the
    # launch's table/lens/cur inputs and donate the pools, so they
    # cannot run under an in-flight launch (the device idles for that
    # window — the N-vs-TTFT trade the README tuning rule names). Every
    # external entry point that reads or mutates slot state
    # (expire_deadlines, evict_stalled, dump_inflight, close) flushes
    # the in-flight launch first, so host state is never stale where
    # it matters, and _notify_complete streams a request's undelivered
    # ring tokens before its completion on every terminal path.

    def _macro_hist(self, chunk_plan: Optional[Dict[str, Any]] = None):
        """Token histories for the in-program draft source (r22): each
        decoding slot's prompt+generated ids right-padded to
        ``[num_slots, max_seq_len]`` (submit() guarantees prompt +
        max_new fits, so the boundary draft and its device twin see
        the SAME history — bit-identical drafts). The chunk-plan slot
        uploads its full prompt so the history is ready the moment the
        program activates it at the final chunk."""
        hcap = int(self.max_seq_len)
        hist = np.zeros((self.num_slots, hcap), np.int32)
        hlen = np.zeros((self.num_slots,), np.int32)
        for i, r in enumerate(self._slots):
            if r is None:
                continue
            if r.state == "decoding":
                t = np.asarray(r.tokens, np.int32)
            elif chunk_plan is not None and i == chunk_plan["slot"]:
                t = np.asarray(r.prompt, np.int32)
            else:
                continue
            t = t[:hcap]
            hist[i, :len(t)] = t
            hlen[i] = len(t)
        return hist, hlen

    def _dispatch_macro(self,
                        chunk_plan: Optional[Dict[str, Any]] = None
                        ) -> bool:
        """Launch ONE macro program covering up to ``multi_step``
        decode steps for every decoding slot. Returns True when a
        launch happened (False: nothing is decoding and no chunk is
        scheduled). Does NOT block: the device handles land in
        ``_pending_macro`` for the next boundary's drain.

        r22: with in-program speculation each iteration is a verify
        step emitting up to k+1 tokens, so the page pre-bind covers
        ``lens + min(N·(k+1), rem)`` and the token histories ship with
        the launch; with a ``chunk_plan`` the launch also carries one
        half-prefilled slot's chained-chunk schedule (the slot enters
        INACTIVE and the program activates it when its final chunk
        lands, so its rem/eos stop bookkeeping rides the launch
        too)."""
        jnp = self._jnp
        n = self.multi_step
        spec_on = self._spec_inprogram
        per_iter = (int(self._spec_cfg.k) + 1) if spec_on else 1
        reqs: Dict[int, DecodeRequest] = {}
        active = np.zeros((self.num_slots,), bool)
        rem = np.zeros((self.num_slots,), np.int32)
        eos = np.full((self.num_slots,), -1, np.int32)
        for i, r in enumerate(self._slots):
            if r is None or r.state != "decoding":
                continue
            r_rem = r.max_new_tokens - len(r.generated)
            active[i] = True
            rem[i] = r_rem
            if r.eos_token is not None:
                eos[i] = int(r.eos_token)
            # pre-bind the launch's growth pages out of the admission
            # reservation (PR 4 contract: cannot fail) — the page
            # table is then a CONSTANT of the program and in-program
            # appends are pure index writes through it. The budget
            # clip inside the program (k_eff) bounds every append
            # below lens + min(N·per_iter, rem), so this covers the
            # speculative worst case exactly.
            self._ensure_pages(
                i, r, int(self._lens[i]) + min(n * per_iter, r_rem))
            reqs[i] = r
        if chunk_plan is not None:
            ci, cr = chunk_plan["slot"], chunk_plan["req"]
            rem[ci] = cr.max_new_tokens
            if cr.eos_token is not None:
                eos[ci] = int(cr.eos_token)
            if chunk_plan["has_final"]:
                # the slot may activate and decode inside THIS launch
                self._ensure_pages(
                    ci, cr, len(cr.prompt)
                    + min(n * per_iter, cr.max_new_tokens))
        if not reqs and chunk_plan is None:
            return False
        has_chunk = chunk_plan is not None
        jit = self._multi_jits.get(has_chunk)
        if jit is None:
            jit = self._build_multi_decode(has_chunk)
            self._multi_jits[has_chunk] = jit
        from ..dispatch import count_op_calls
        args = [self._fresh_state(), self._pools,
                jnp.asarray(self._table), jnp.asarray(self._lens),
                jnp.asarray(self._cur), jnp.asarray(active),
                jnp.asarray(rem), jnp.asarray(eos)]
        if spec_on:
            hist, hlen = self._macro_hist(chunk_plan)
            args += [jnp.asarray(hist), jnp.asarray(hlen)]
        if has_chunk:
            args += [jnp.asarray(chunk_plan["ids"]),
                     jnp.asarray(chunk_plan["valid"]),
                     jnp.asarray(chunk_plan["start"]),
                     jnp.asarray(chunk_plan["final"]),
                     jnp.asarray(np.int32(chunk_plan["count"])),
                     jnp.asarray(np.int32(chunk_plan["slot"]))]
        args = tuple(args)
        t0 = time.monotonic()
        with count_op_calls() as c:
            ring, nsteps, cur, lens, act, pools = jit(*args)
        self._record_programs("decode_multi", c.count)
        if c.count:
            self._capture_cost("decode_multi", jit, args)
        self._pools = pools
        self.macro_launches += 1
        self._pending_macro = {
            "ring": ring, "nsteps": nsteps, "cur": cur, "lens": lens,
            "reqs": reqs, "t_dispatch": t0,
            "launch": self.macro_launches,
            "dispatch_ms": (time.monotonic() - t0) * 1e3,
            "rem": rem, "chunk": chunk_plan,
        }
        return True

    def _drain_macro(self) -> List[Tuple]:
        """Block on the in-flight macro launch (if any) and fold its
        ring into host state: generated token lists, per-slot
        lens/cur, finished-slot teardown (pages freed, reservations
        returned — notify deferred), the per-launch decode EMA and
        the step-timeline macro record. Returns the emission schedule
        ``[(req, token, done)]`` in exact (in-macro step, slot) order
        — the same order ``multi_step=1`` streams — WITHOUT delivering
        it: the boundary delivers after the next launch is dispatched
        (host/device overlap), and _notify_complete flushes a
        terminating request's share first."""
        pend = self._pending_macro
        if pend is None:
            return []
        # cleared BEFORE the blocking read: a failed async computation
        # raises here, and retrying dead handles would only re-raise
        self._pending_macro = None
        t_wait = time.monotonic()
        ring = np.asarray(pend["ring"])  # blocks until the launch ends
        idle_s = time.monotonic() - t_wait
        nsteps = int(pend["nsteps"])
        lens_f = np.asarray(pend["lens"])
        cur_f = np.asarray(pend["cur"])
        now = time.monotonic()
        self._last_macro_t = now
        dt = now - pend["t_dispatch"]
        # per-MACRO-LAUNCH decode EMA (the r19 satellite):
        # decode_ema_s now tracks one dispatch->drain launch window;
        # per-token estimates derive as ema/multi_step and the
        # deadline gate charges ceil(need/multi_step) launches
        # (_deadline_hopeless). First launch is compile-dominated —
        # skip it, the same warmup rule as the per-token EMA.
        if self._macro_warm:
            self.decode_ema_s = dt if self.decode_ema_s is None \
                else 0.8 * self.decode_ema_s + 0.2 * dt
        else:
            self._macro_warm = True
        reqs = dict(pend["reqs"])
        plan = pend.get("chunk")
        spec_mode = ring.ndim == 3
        k = int(self._spec_cfg.k) if spec_mode else 0
        # --- fold the in-program chunk plan (r22) -----------------------
        # All of the plan's chunks ran (the program's cond keeps the
        # loop alive through iteration count-1 even when every decode
        # slot stopped), so the host bookkeeping is unconditional; the
        # final chunk's first token, if any, sits in the ring at
        # final_idx and the slot joins the generic fold below.
        if plan is not None:
            ci = plan["slot"]
            creq = plan["req"]
            if self._slots[ci] is creq and \
                    creq.state == "prefill_partial":
                creq.stats.prefill_chunks += plan["count"]
                creq.prefill_done_len = plan["end"]
                self._lens[ci] = plan["end"]
                creq.last_emit_t = now
                self._last_chunk_t = now
                creq.chunk_deferrals = 0
                if creq.trace is not None:
                    creq.trace.add(
                        "prefill_chunk_inprogram",
                        pend["t_dispatch"] * 1e6, now * 1e6,
                        parent=creq.span, chunks=plan["count"],
                        tokens=plan["tokens"], launch=pend["launch"])
                if creq.deadline_t is not None and \
                        now >= creq.deadline_t:
                    # expired mid-prefill: chunks are paid for, but a
                    # token past the deadline breaks the contract —
                    # same typed eviction as the boundary path
                    self._evict_slot(ci, "deadline")
                elif plan["has_final"]:
                    # promote: the final chunk's logits produced the
                    # first token inside the program — same shape as
                    # the boundary promotion in _advance_prefill_chunk
                    fj = plan["final_idx"]
                    nxt0 = int(ring[ci, fj, 0] if spec_mode
                               else ring[ci, fj])
                    creq.stats.prefill_attempts += 1
                    creq.stats.first_token_t = now
                    creq.state = "decoding"
                    if creq.trace is not None:
                        self._tr_end(creq,
                                     chunks=creq.stats.prefill_chunks)
                        creq.trace.event("first_token",
                                         parent=creq.trace.anchor,
                                         token=nxt0)
                        creq.span = creq.trace.begin(
                            "decode", parent=creq.trace.anchor)
                    if self._prefix_cache is not None:
                        creq.cache_keys = self._prefix_cache.insert(
                            creq.prompt, self._table[ci],
                            self.allocator, creq.req_id,
                            self.page_size, creq.cache_keys,
                            device_hits=getattr(
                                creq, "_pfx_device_hits", None))
                    # join the generic ring/lens/finish fold: its
                    # first token (and any decode tokens the program
                    # ran after activation) stream in ring order
                    reqs[ci] = creq
        emissions: List[Tuple] = []
        per_step_tokens: List[int] = []
        emitted_ct = {i: 0 for i in reqs}
        runs_tot = drafted_tot = accepted_tot = 0
        rem0 = pend.get("rem")
        for j in range(nsteps):
            count = 0
            for i in sorted(reqs):
                req = reqs[i]
                if spec_mode:
                    toks = []
                    for t in ring[i, j]:
                        t = int(t)
                        if t < 0:
                            break  # run entries are front-packed
                        toks.append(t)
                else:
                    t = int(ring[i, j])
                    toks = [t] if t >= 0 else []
                if not toks:
                    continue
                if spec_mode and not (plan is not None
                                      and i == plan["slot"]
                                      and j == plan["final_idx"]):
                    # reconstruct the per-verify-step stats the
                    # boundary path records on the host: drafted =
                    # the budget-clipped k_eff the program used,
                    # accepted = run length minus the correction/
                    # bonus token (an EOS inside an accepted run
                    # truncates the recorded run — terminal, rare)
                    k_eff = max(
                        min(k, int(rem0[i]) - emitted_ct[i] - 1), 0)
                    req.stats.spec_steps += 1
                    req.stats.spec_drafted += k_eff
                    req.stats.spec_accepted += max(len(toks) - 1, 0)
                    runs_tot += 1
                    drafted_tot += k_eff
                    accepted_tot += max(len(toks) - 1, 0)
                emitted_ct[i] += len(toks)
                for tok in toks:
                    req.generated.append(tok)
                    req.stats.tokens_out = len(req.generated)
                    emissions.append((req, tok, self._finish_due(req)))
                count += len(toks)
            per_step_tokens.append(count)
        for i in sorted(reqs):
            req = reqs[i]
            if self._slots[i] is not req:
                continue  # defensive: slot reassigned (cannot happen
                # under the flush discipline, but never corrupt it)
            self._lens[i] = int(lens_f[i])
            self._cur[i] = int(cur_f[i])
            if self._finish_due(req):
                # teardown now (pages/reservations back before the
                # boundary's admission), notify at delivery — after
                # the request's ring tokens have streamed
                self._finish_slot(i, notify=False)
            elif spec_mode:
                # in-program rejection rollback (r22): the program
                # rewound seq_lens past the rejected drafts; return
                # the pages whose every position sits at or beyond
                # the accepted length (rereserve — later growth still
                # cannot fail). Finished slots freed everything above.
                self._rollback_pages(i, req, int(lens_f[i]))
            if req.trace is not None:
                req.trace.add("macro_step", pend["t_dispatch"] * 1e6,
                              now * 1e6, parent=req.span,
                              step=self.steps + nsteps,
                              launch=pend["launch"],
                              steps_run=nsteps,
                              tokens=emitted_ct.get(i, 0))
        self.steps += nsteps
        # step-timeline macro record (r16 ring, r19 fields): the entry
        # committed for THIS boundary carries the drained launch's
        # attribution; per_token_timeline() reconstructs per-step rows
        self._tl_add_ms("decode_ms", dt)
        self._tl_add_ms("overlap_idle_ms", idle_s)
        self._tl_macro = {
            "launch": pend["launch"], "steps": nsteps,
            "tokens": int(sum(per_step_tokens)),
            "per_step_tokens": per_step_tokens,
            "ms": round(dt * 1e3, 4),
            "overlap_idle_ms": round(idle_s * 1e3, 4),
            "dispatch_ms": round(pend["dispatch_ms"], 4),
        }
        if spec_mode:
            # r22 additive keys: verify iterations broken out so the
            # timeline can attribute macro time to speculation
            self._tl_macro["spec"] = {
                "runs": runs_tot, "drafted": drafted_tot,
                "accepted": accepted_tot}
        if plan is not None:
            self._tl_macro["chunks"] = int(plan["count"])
        return emissions

    def _flush_macro(self) -> None:
        """EXTERNAL-entry drain: block on any in-flight macro launch
        AND deliver everything pending immediately — callbacks,
        completion notifications included. Called by every public
        entry point that reads or mutates slot state
        (expire_deadlines, evict_stalled, dump_inflight, close), so
        outside a boundary there is never a request whose tokens were
        folded but whose completion is still owed (the resurrection
        path depends on this: a request finishing inside a flushed
        launch must answer its client BEFORE the completion hook is
        detached, or the client hangs). The boundary itself
        (_macro_multi_step) drains WITHOUT this helper and defers
        delivery past the next dispatch — that is the overlap."""
        if self._pending_macro is not None:
            self._pending_emit.extend(self._drain_macro())
        self._deliver_pending()

    def _flush_req_emissions(self, req: DecodeRequest) -> None:
        """Stream ONE request's undelivered ring tokens (terminal-path
        ordering: tokens before completion). No-op for requests with
        nothing pending."""
        if not self._pending_emit:
            return
        mine = [e for e in self._pending_emit if e[0] is req]
        if not mine:
            return
        self._pending_emit = [e for e in self._pending_emit
                              if e[0] is not req]
        for _req, tok, done in mine:
            req.last_emit_t = time.monotonic()
            if req.on_token is not None:
                req.on_token(req.req_id, tok, done)

    def _deliver_pending(self) -> None:
        """Deliver the drained emission schedule in order — on_token
        callbacks, stall-watchdog liveness, completion notifications
        for requests that finished inside the launch. Runs AFTER the
        next launch is dispatched, so callback/tracing/metrics work
        overlaps device compute."""
        while self._pending_emit:
            req, tok, done = self._pending_emit.pop(0)
            req.last_emit_t = time.monotonic()
            if req.on_token is not None:
                req.on_token(req.req_id, tok, done)
            if done and req.done:
                # the request's terminal bookkeeping ran at drain
                # (notify deferred to exactly here, after its tokens)
                self._notify_complete(req)

    def _macro_multi_step(self) -> int:
        """One multi-step boundary: drain launch K−1, run the host
        boundary work (deadline/stall sweeps, admission, one chunked-
        prefill advance), dispatch launch K, then deliver ring K−1's
        tokens while the device runs K."""
        emissions = self._drain_macro()
        if emissions:
            self._pending_emit.extend(emissions)
        # the INNER sweeps: the public wrappers would flush-and-
        # deliver the emissions just drained, forfeiting the overlap
        self._expire_deadlines_inner()
        self._evict_stalled_inner()
        self._admit()
        if self.num_active == 0:
            self._deliver_pending()
            return 0
        chunk_plan = None
        if self.prefill_chunk_tokens is not None:
            if self._chunk_inprogram:
                # r22: chained chunks ride INSIDE the launch (up to N
                # of one slot's chunks, one per iteration); only the
                # dense fresh first chunk still runs here at the
                # boundary (inside _plan_inprogram_chunks)
                chunk_plan = self._plan_inprogram_chunks()
            else:
                self._advance_prefill_chunk()
        self._dispatch_macro(chunk_plan)
        self._deliver_pending()
        return self.num_active

    def per_token_timeline(self) -> List[Dict[str, Any]]:
        """Step-timeline view with macro-launch entries expanded back
        into per-token-step rows (the r19 observability contract: the
        ring marks macro launches; this reconstructs the per-step
        attribution a per-token engine's ring would have carried).
        Non-macro entries pass through unchanged."""
        out: List[Dict[str, Any]] = []
        for entry in self.timeline:
            macro = entry.get("macro")
            if not macro or not macro.get("steps"):
                out.append(dict(entry))
                continue
            nsteps = macro["steps"]
            base = entry["step"] - nsteps
            for j, toks in enumerate(macro["per_step_tokens"]):
                out.append({
                    "step": base + j + 1,
                    "ms": round(macro["ms"] / nsteps, 4),
                    "tokens": toks,
                    "macro_launch": macro["launch"],
                    "macro_offset": j,
                })
        return out

    # -- speculative decoding ----------------------------------------------

    def _ensure_pages(self, slot: int, req: DecodeRequest,
                      need_len: int) -> None:
        """Grow the slot's page set to cover positions [0, need_len)
        out of the request's reservation (guaranteed: capacity was
        committed at admission). Reserve-growth modes only
        (speculative, and multi-step macro dispatch) — vanilla
        per-token admission binds every page up front."""
        row = self._table[slot]
        want = -(-need_len // self.page_size)
        missing = [j for j in range(want) if row[j] == self._scratch]
        if not missing:
            return
        reason = ("spec_grow" if self._spec_cfg is not None
                  else "macro_grow")
        with self._led(reason, req.req_id):
            pages = self.allocator.alloc_reserved(req.req_id,
                                                  len(missing))
        for j, p in zip(missing, pages):
            row[j] = p

    def _rollback_pages(self, slot: int, req: DecodeRequest,
                        new_len: int) -> int:
        """Rejection rollback: pages whose EVERY position sits at or
        beyond the accepted length hold only rejected-draft KV —
        return them to the allocator (capacity goes back into the
        request's reservation, so later growth still cannot fail).
        The page containing position ``new_len`` (the next append
        target) is kept even when partially stale: stale positions are
        never attended (host seq_lens were rewound) and the next
        append overwrites them. Shared prefix pages sit strictly below
        ``new_len`` and are never touched."""
        row = self._table[slot]
        keep = -(-(new_len + 1) // self.page_size)
        victims = [int(row[j]) for j in range(keep, self.max_pages)
                   if row[j] != self._scratch]
        if victims:
            with self._led("spec_rollback", req.req_id):
                self.allocator.release_pages(req.req_id, victims,
                                             rereserve=True)
            row[keep:] = self._scratch
        return len(victims)

    def _spec_step(self) -> int:
        """One draft-and-verify step over every active slot: propose k
        tokens per slot (host/draft-model), score all k+1 positions in
        ONE target forward, emit each slot's longest accepted prefix
        plus its correction/bonus token, rewind ``seq_lens`` past the
        rejections and return wholly-unused pages. Greedy emission is
        bit-identical to the vanilla per-token loop (pinned)."""
        import jax

        jnp = self._jnp
        cfg = self._spec_cfg
        k = cfg.k
        vocab = self.cfg.vocab_size
        # half-prefilled slots (chunked mode) are NOT verified: their
        # valid count stays 0, parking their writes on the scratch page
        # exactly like empty slots, and the draft source sees no
        # history for them
        active = [i for i, r in enumerate(self._slots)
                  if r is not None and r.state == "decoding"]
        hist = [None if (r is None or r.state != "decoding")
                else r.tokens for r in self._slots]
        drafts = np.asarray(self._spec_draft.propose(hist, k), np.int32)
        if drafts.shape != (self.num_slots, k):
            raise ValueError(
                f"draft source returned shape {drafts.shape}, expected "
                f"{(self.num_slots, k)}")
        # defensive clip: a draft over a larger vocab must not feed the
        # target an out-of-range id (wrong guesses are free, OOB isn't)
        drafts = np.clip(drafts, 0, vocab - 1).astype(np.int32)
        tokens = np.zeros((self.num_slots, k + 1), np.int32)
        tokens[:, 0] = self._cur
        tokens[:, 1:] = drafts
        valid = np.zeros((self.num_slots,), np.int32)
        old_lens = self._lens.copy()
        for i in active:
            req = self._slots[i]
            rem = req.max_new_tokens - len(req.generated)
            k_eff = min(k, rem - 1)  # emit at most rem tokens
            valid[i] = 1 + k_eff
            self._ensure_pages(i, req, int(old_lens[i]) + int(valid[i]))
        if self._verify_jit is None:
            self._verify_jit = self._build_verify()
        if cfg.temperature and self._spec_key is None:
            self._spec_key = jax.random.PRNGKey(cfg.seed)
        if cfg.temperature:
            self._spec_key, key = jax.random.split(self._spec_key)
        else:
            key = jax.random.PRNGKey(0)  # unused on the greedy path

        def run_verify():
            from ..dispatch import count_op_calls
            from ..distributed.fault_inject import fault_point
            self._check_pools_live("verify")
            fault_point("serving.verify")
            args = (self._fresh_state(), self._pools,
                    jnp.asarray(self._table), jnp.asarray(self._lens),
                    jnp.asarray(tokens), jnp.asarray(valid), key)
            with count_op_calls() as c:
                out = self._verify_jit(*args)
            self._record_programs("verify", c.count)
            if c.count:
                self._capture_cost("verify", self._verify_jit, args)
            return out

        t0v = time.monotonic()
        if self._verify_retry is not None:
            accept, resid, full, pools = self._verify_retry.call(
                run_verify, site="serving.verify")
        else:
            accept, resid, full, pools = run_verify()
        t1v = time.monotonic()
        self._tl_add_ms("verify_ms", t1v - t0v)
        self._pools = pools
        accept = np.asarray(accept)
        resid = np.asarray(resid)
        full = np.asarray(full)
        self.steps += 1
        for i in active:
            req = self._slots[i]
            k_eff = int(valid[i]) - 1
            n = 0
            while n < k_eff and accept[i, n]:
                n += 1
            req.stats.spec_steps += 1
            req.stats.spec_drafted += k_eff
            req.stats.spec_accepted += n
            if req.trace is not None:
                req.trace.add("verify_step", t0v * 1e6, t1v * 1e6,
                              parent=req.span, step=self.steps,
                              drafted=k_eff, accepted=n)
            nxt = int(resid[i, n]) if n < k_eff else int(full[i, k_eff])
            emitted = [int(t) for t in tokens[i, 1:1 + n]] + [nxt]
            finished = False
            for tok in emitted:
                req.generated.append(tok)
                req.stats.tokens_out = len(req.generated)
                self._cur[i] = tok
                self._emit_token(req, tok)
                if self._finish_due(req):
                    finished = True
                    break  # EOS inside the accepted run: stop emitting
            if finished:
                # _maybe_finish frees the slot wholesale (pages AND
                # remaining reservation) — no rollback bookkeeping
                self._maybe_finish(i)
                continue
            # KV now validly covers cur + the n accepted drafts; the
            # last emitted token's KV is written by the NEXT step
            new_len = int(old_lens[i]) + n + 1
            self._lens[i] = new_len
            self._rollback_pages(i, req, new_len)
        return self.num_active

    def step(self) -> int:
        """Admit what fits, spend the chunked-prefill budget (at most
        one slot's next chunk), run ONE fixed-shape decode step (or one
        draft-and-verify speculative step) for every slot past prefill,
        evict what finished. Returns the number of still-active slots.
        The ``engine.step`` fault site fires FIRST — before admission
        and before the donating jit — so an injected step failure
        leaves host and device state exactly as the previous step left
        them (the precondition for the serving layer's resurrection
        replay)."""
        from ..distributed.fault_inject import fault_point
        fault_point("engine.step")
        # r16 step timeline: reset per-step accumulators, commit one
        # ring entry per step attempt (a dict per STEP — never per
        # token — next to at least one jit launch)
        self._tl_programs = {}
        self._tl_ms = {}
        if self.ledger is not None:
            self.ledger.step = self.steps
        t_step = time.monotonic()
        try:
            return self._step_inner()
        finally:
            self._tl_commit(t_step)

    def _step_inner(self) -> int:
        if self.multi_step > 1 and (self._spec_cfg is None
                                    or self._spec_inprogram):
            # device-resident multi-step decode (r19): one boundary =
            # drain launch K−1, boundary scheduling, dispatch launch
            # K, deliver K−1's ring. r22: a greedy speculative engine
            # with a device-implementable draft rides the SAME macro
            # boundary — draft/verify/rewind run inside the launch
            # (_spec_inprogram). Other speculative engines (sampled
            # verify, host draft sources) keep their per-step verify
            # cadence — spec composes AT the boundary for them.
            return self._macro_multi_step()
        self.expire_deadlines()
        self.evict_stalled()
        self._admit()
        if self.num_active == 0:
            return 0
        if self.prefill_chunk_tokens is not None:
            self._advance_prefill_chunk()
        if not any(r is not None and r.state == "decoding"
                   for r in self._slots):
            # everything active is still mid-prefill (chunked mode):
            # no decode step to run; the next step() advances the next
            # chunk. num_active keeps run() looping.
            return self.num_active
        t0 = time.monotonic()
        try:
            if self._spec_cfg is not None:
                return self._spec_step()
            return self._decode_step()
        finally:
            # skip the first step: its wall time is dominated by the
            # one-off decode/prefill compiles and would poison the
            # deadline gate's estimate for the engine's whole warmup.
            # Only the decode/verify call is timed — chunk prefills
            # have their own EMA (_advance_prefill_chunk), so a
            # prefill-heavy step can't poison the per-token estimate.
            if self.steps > 1:
                dt = time.monotonic() - t0
                self.decode_ema_s = dt if self.decode_ema_s is None \
                    else 0.8 * self.decode_ema_s + 0.2 * dt

    def _decode_step(self) -> int:
        jnp = self._jnp
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        decoding = np.array([r is not None and r.state == "decoding"
                             for r in self._slots])
        table, lens = self._table, self._lens
        if any(r is not None and r.state == "prefill_partial"
               for r in self._slots):
            # half-prefilled slots ride the fixed-shape step MASKED to
            # the scratch page at length 0: their pages hold a partial
            # prompt whose next position the NEXT chunk owns — the
            # decode append must not touch it (writes land on scratch,
            # attention over an empty slot is defined zeros). Host
            # lens/table keep the real values; only the device call
            # sees the mask.
            table = np.where(decoding[:, None], table,
                             self._scratch).astype(np.int32)
            lens = np.where(decoding, lens, 0).astype(np.int32)
        from ..dispatch import count_op_calls
        args = (self._fresh_state(), self._pools,
                jnp.asarray(table), jnp.asarray(lens),
                jnp.asarray(self._cur))
        t0d = time.monotonic()
        with count_op_calls() as c:
            nxt, pools, lens_new = self._decode_jit(*args)
        t1d = time.monotonic()
        self._tl_add_ms("decode_ms", t1d - t0d)
        self._record_programs("decode", c.count)
        if c.count:
            self._capture_cost("decode", self._decode_jit, args)
        self._pools = pools
        nxt = np.asarray(nxt)
        # non-decoding slots wrote to the scratch page; keep their host
        # length (0 for empty slots, prefill_done_len for half-
        # prefilled ones)
        self._lens = np.where(decoding, np.asarray(lens_new),
                              self._lens).astype(np.int32)
        self.steps += 1
        for slot, req in enumerate(self._slots):
            if req is None or req.state != "decoding":
                continue
            tok = int(nxt[slot])
            req.generated.append(tok)
            req.stats.tokens_out = len(req.generated)
            self._cur[slot] = tok
            if req.trace is not None:
                # pre-timed closed span: one list append per traced
                # in-flight request, no extra clock reads per slot
                req.trace.add("decode_step", t0d * 1e6, t1d * 1e6,
                              parent=req.span, step=self.steps,
                              token=tok)
            self._emit_token(req, tok)
            self._maybe_finish(slot)
        return self.num_active

    def run(self, max_steps: int = 100000) -> Dict[int, np.ndarray]:
        """Drive until queue and slots drain; returns {req_id: tokens}
        for everything finished so far and DRAINS the finished store
        (a long-running engine must not accumulate past results —
        callers polling step() themselves use result(id, pop=True))."""
        steps = 0
        while self._queue or self.num_active:
            before = (len(self._queue), self.num_active)
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} "
                                   f"steps (state {before})")
        if self._prefix_cache is None:
            self.allocator.check_no_leak()
        else:
            # cached prefix pages legitimately outlive their requests;
            # audit the cache's books against the allocator instead
            self._prefix_cache.check_consistent(self.allocator)
        out = {rid: req.tokens for rid, req in self._finished.items()}
        self._finished.clear()
        return out

    def close(self) -> None:
        """Terminal teardown: evict every active slot, drop every
        queued request, return their pages, clear the prefix cache, and
        assert nothing leaked. After close() the engine holds no pages
        — the graceful-drain endpoint bench/tests call on every exit
        path (a drained `run()` followed by close() is the clean
        shutdown; close() mid-flight is the hard stop)."""
        # multi-step (r19): drain + deliver any in-flight launch so
        # teardown evictions see current state and streamed tokens
        # precede every eviction notification. A failed drain means
        # the launch's tokens never existed for any client — drop it
        # (anything drained EARLIER still delivers).
        try:
            self._flush_macro()
        except Exception:
            self._pending_macro = None
            self._deliver_pending()
        for slot, req in enumerate(self._slots):
            if req is not None:
                self._evict_slot(slot, "evicted")
        for req in list(self._queue):
            self._terminate_queued(req, "evicted")
        if self._prefix_cache is not None:
            with self._led("close"):
                self._prefix_cache.clear(self.allocator)
        self.allocator.check_no_leak()


def create_decode_engine(model, **kwargs) -> ContinuousBatchingEngine:
    """Serving-path entry (mirrors inference.create_predictor): build a
    continuous-batching decode engine over a causal-LM layer."""
    return ContinuousBatchingEngine(model, **kwargs)
