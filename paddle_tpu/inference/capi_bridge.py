"""Marshalling bridge for the C inference API (native/pt_capi.cc).

Reference parity: paddle/fluid/inference/capi_exp/ wraps
AnalysisPredictor behind a C ABI for deployment from C/C++/Go. Here the
C library embeds CPython and calls these helpers; payloads cross the
boundary as raw bytes + (shape, dtype) so the C side needs no numpy
headers.

Everything is keyed by integer handles so the C side holds no Python
pointers beyond the module itself.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Tuple

import numpy as np

_handles: Dict[int, "object"] = {}
_ids = itertools.count(1)

_DTYPES = {"float32": np.float32, "float16": np.float16,
           "int32": np.int32, "int64": np.int64, "uint8": np.uint8,
           "bool": np.bool_}


def create(prefix: str, precision: str = "float32",
           device: str = "auto") -> int:
    if device == "cpu":
        # a C host cannot set JAX_PLATFORMS after process start; honor
        # PD_ConfigDisableGpu here, before the first backend touch
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized; keep it
    from .predictor import Config, Predictor
    cfg = Config(prefix)
    cfg.set_precision(precision)
    if device == "cpu":
        cfg.disable_gpu()
    h = next(_ids)
    _handles[h] = Predictor(cfg)
    return h


def _p(h: int):
    p = _handles.get(h)
    if p is None:
        raise KeyError(f"invalid predictor handle {h}")
    return p


def input_names(h: int) -> List[str]:
    return _p(h).get_input_names()


def set_input(h: int, name: str, data: bytes, shape: Tuple[int, ...],
              dtype: str) -> None:
    arr = np.frombuffer(data, _DTYPES[dtype]).reshape(shape)
    _p(h).get_input_handle(name).copy_from_cpu(arr)


def run(h: int) -> int:
    p = _p(h)
    p.run()
    return len(p.get_output_names())


def output_names(h: int) -> List[str]:
    return _p(h).get_output_names()


def get_output(h: int, name: str) -> Tuple[bytes, Tuple[int, ...], str]:
    arr = np.ascontiguousarray(_p(h).get_output_handle(name).copy_to_cpu())
    return arr.tobytes(), tuple(int(s) for s in arr.shape), str(arr.dtype)


def destroy(h: int) -> None:
    _handles.pop(h, None)
