"""paddle_tpu.fft — spectral transforms namespace.

Reference parity: python/paddle/fft.py (paddle.fft.*). Autograd-aware
wrapped versions of ops/fft.py kernels: eager calls record on the tape,
jitted callers get the raw kernels via paddle_tpu.ops.fft.
"""

from . import dispatch as _dispatch
from .ops import fft as _kernels

_NAMES = [n for n in dir(_kernels) if not n.startswith("_")
          and callable(getattr(_kernels, n))
          and getattr(_kernels, n).__module__ == _kernels.__name__]

for _n in _NAMES:
    globals()[_n] = _dispatch.wrap_op(_n)

__all__ = sorted(_NAMES)
del _n
