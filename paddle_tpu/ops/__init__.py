"""Pure functional op library (jax-native kernels).

The modules here are raw jax functions — safe inside jit/pjit/grad. The
eager Tensor-wrapping dispatch layer is paddle_tpu.dispatch. Every public
function is auto-registered in the op registry so the OpTest harness and
eager dispatcher can enumerate them.
"""

import inspect as _inspect

from . import creation, decode_extra, detection, fft, linalg, \
    loss_extra, manipulation, math, math_extra, nn_functional, random, \
    rnn, search, sequence, vision_extra
from .registry import OpDef, all_ops, get_op, has_op, register_op

_DYNAMIC_SHAPE_OPS = {
    "nonzero", "masked_select", "unique", "unique_consecutive", "where",
    "sequence_unpad", "bincount",
}
_NON_DIFF_OPS = {
    "argmax", "argmin", "argsort", "randint", "randperm", "one_hot",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "isnan",
    "isinf", "isfinite", "shape", "numel", "count_nonzero",
    "is_empty", "broadcast_shape",
    "nms", "multiclass_nms", "bipartite_match",
    "crf_decoding", "gather_tree", "beam_search_decode", "shuffle_batch",
    "digitize", "bitwise_left_shift", "bitwise_right_shift",
    "is_complex", "is_floating_point", "rank",
}


def _auto_register():
    for mod in (creation, math, manipulation, search, linalg, random,
                nn_functional, rnn, sequence, detection, loss_extra,
                vision_extra, decode_extra, math_extra, fft):
        short = mod.__name__.rsplit(".", 1)[-1]
        for name, fn in vars(mod).items():
            if name.startswith("_") or not callable(fn):
                continue
            if not _inspect.isfunction(fn) or fn.__module__ != mod.__name__:
                continue
            if not has_op(name):
                register_op(name, fn, module=short,
                            differentiable=name not in _NON_DIFF_OPS,
                            dynamic_shape=name in _DYNAMIC_SHAPE_OPS)


_auto_register()
