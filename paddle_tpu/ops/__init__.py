"""Pure functional op library (jax-native kernels).

The modules here are raw jax functions — safe inside jit/pjit/grad. The
eager Tensor-wrapping dispatch layer is paddle_tpu.dispatch. Every public
function is auto-registered in the op registry so the OpTest harness and
eager dispatcher can enumerate them.
"""

import inspect as _inspect

from . import creation, decode_extra, detection, fft, linalg, \
    loss_extra, manipulation, math, math_extra, metric_extra, \
    nlp_ctr_extra, nn_functional, random, \
    rnn, search, sequence, vision_extra
from .registry import OpDef, all_ops, get_op, has_op, register_op

_DYNAMIC_SHAPE_OPS = {
    "nonzero", "masked_select", "unique", "unique_consecutive", "where",
    "sequence_unpad", "bincount",
    "chunk_eval", "detection_map", "positive_negative_pair",
    "rpn_target_assign", "distribute_fpn_proposals",
    "collect_fpn_proposals", "mine_hard_examples", "locality_aware_nms",
    "filter_by_instag", "tdm_sampler", "similarity_focus",
    "read_file", "decode_jpeg", "retinanet_target_assign",
    "retinanet_detection_output", "generate_proposal_labels",
    "generate_mask_labels",
}
_NON_DIFF_OPS = {
    "argmax", "argmin", "argsort", "randint", "randperm", "one_hot",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "isnan",
    "isinf", "isfinite", "shape", "numel", "count_nonzero",
    "is_empty", "broadcast_shape",
    "edit_distance", "ctc_align", "mean_iou", "precision_recall",
    "chunk_eval", "detection_map", "positive_negative_pair",
    "density_prior_box", "target_assign", "rpn_target_assign",
    "generate_proposals", "matrix_nms", "distribute_fpn_proposals",
    "collect_fpn_proposals", "mine_hard_examples", "locality_aware_nms",
    "polygon_box_transform", "hash_ids", "sampling_id", "tdm_child",
    "tdm_sampler", "filter_by_instag", "similarity_focus",
    "nms", "multiclass_nms", "bipartite_match",
    "read_file", "decode_jpeg", "retinanet_target_assign",
    "retinanet_detection_output", "generate_proposal_labels",
    "generate_mask_labels",
    "paged_attention", "paged_attention_head_sharded",
    "paged_attention_fused", "fused_sample", "paged_page_splice",
    "crf_decoding", "gather_tree", "beam_search_decode", "shuffle_batch",
    "digitize", "bitwise_left_shift", "bitwise_right_shift",
    "is_complex", "is_floating_point", "rank",
}


def _auto_register():
    for mod in (creation, math, manipulation, search, linalg, random,
                nn_functional, rnn, sequence, detection, loss_extra,
                vision_extra, decode_extra, math_extra, fft,
                metric_extra, nlp_ctr_extra):
        short = mod.__name__.rsplit(".", 1)[-1]
        for name, fn in vars(mod).items():
            if name.startswith("_") or not callable(fn):
                continue
            if not _inspect.isfunction(fn) or fn.__module__ != mod.__name__:
                continue
            if not has_op(name):
                register_op(name, fn, module=short,
                            differentiable=name not in _NON_DIFF_OPS,
                            dynamic_shape=name in _DYNAMIC_SHAPE_OPS)


_auto_register()
