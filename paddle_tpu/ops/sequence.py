"""Sequence ops over the padded+lengths device representation.

Reference parity: paddle/fluid/operators/sequence_ops/ (49 files —
sequence_pool, sequence_softmax, sequence_expand, sequence_reverse,
sequence_pad/unpad, sequence_slice, sequence_enumerate, sequence_conv...).
The reference kernels walk LoD offsets; here every op takes a dense
``x [batch, maxlen, ...]`` plus int ``lengths [batch]`` and works through
masks so it stays jittable with static shapes (SURVEY §2.1 "Tensor & IR
types" row). Host-side ragged data uses framework.ragged.RaggedTensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _valid_mask(lengths, maxlen):
    return jnp.arange(maxlen)[None, :] < jnp.asarray(lengths)[:, None]


def _expand_mask(mask, x):
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


def sequence_pad(x, lengths, pad_value=0.0, padded_length=-1):
    """Force padding positions of an already-dense batch to ``pad_value``
    (ref sequence_pad_op.cc semantics on the device representation).
    ``padded_length`` fixes the output time dimension (the reference
    attr; -1 = the batch's current max, i.e. x.shape[1]) — shorter
    truncates is an error in the reference, so it must be >= every
    length; longer right-pads with ``pad_value``."""
    m = x.shape[1]
    if padded_length >= 0 and padded_length != m:
        if padded_length < m:
            # dropping buffer columns is only legal when they are all
            # padding; with concrete lengths enforce it like the
            # reference (sequence_pad_op: padded_length must cover
            # every sequence). With TRACED lengths the check cannot run
            # at trace time, so it moves to run time: a debug callback
            # re-checks max(lengths) on the host and FAILS the jitted
            # computation (XlaRuntimeError) instead of silently
            # truncating real timesteps.
            try:
                max_len = int(np.max(np.asarray(lengths)))
            except (jax.errors.ConcretizationTypeError, TypeError):
                max_len = None  # traced: deferred to the run-time check

                def _runtime_cover_check(lv, _pl=padded_length):
                    got = int(np.max(np.asarray(lv))) if np.size(lv) \
                        else 0
                    if got > _pl:
                        raise ValueError(
                            f"sequence_pad: padded_length={_pl} is "
                            f"shorter than the longest sequence "
                            f"({got}) — the reference op rejects this "
                            "(truncation is never implicit)")

                jax.debug.callback(_runtime_cover_check,
                                   jnp.asarray(lengths))
            if max_len is not None and padded_length < max_len:
                raise ValueError(
                    f"sequence_pad: padded_length={padded_length} is "
                    f"shorter than the longest sequence ({max_len}) — "
                    "the reference op rejects this (truncation is "
                    "never implicit)")
            x = x[:, :padded_length]
            m = padded_length
        else:
            pad = [(0, 0), (0, padded_length - m)] + \
                [(0, 0)] * (x.ndim - 2)
            x = jnp.pad(x, pad)
            m = padded_length
    mask = _expand_mask(_valid_mask(lengths, m), x)
    return jnp.where(mask, x, jnp.asarray(pad_value, dtype=x.dtype))


def sequence_pool(x, lengths, pool_type="sum"):
    """Pool each sequence's valid prefix. pool_type: sum|mean|sqrt|max|min|
    first|last (ref sequence_pool_op.h SequencePoolFunctor)."""
    n, m = x.shape[0], x.shape[1]
    mask = _expand_mask(_valid_mask(lengths, m), x)
    lengths = jnp.asarray(lengths)
    denom_shape = (n,) + (1,) * (x.ndim - 2)
    len_b = jnp.maximum(lengths, 1).astype(x.dtype).reshape(denom_shape)
    if pool_type == "sum":
        return jnp.where(mask, x, 0).sum(axis=1)
    if pool_type == "mean":
        return jnp.where(mask, x, 0).sum(axis=1) / len_b
    if pool_type == "sqrt":
        return jnp.where(mask, x, 0).sum(axis=1) / jnp.sqrt(len_b)
    if pool_type == "max":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jnp.where(mask, x, neg).max(axis=1)
    if pool_type == "min":
        pos = jnp.finfo(x.dtype).max if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).max
        return jnp.where(mask, x, pos).min(axis=1)
    if pool_type == "first":
        ok = (lengths > 0).reshape(denom_shape)
        return jnp.where(ok, x[:, 0], jnp.zeros((), x.dtype))
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        last = jnp.take_along_axis(
            x, idx.reshape((n, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
        ok = (lengths > 0).reshape(denom_shape)
        return jnp.where(ok, last, jnp.zeros((), x.dtype))
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, "last")


def sequence_softmax(x, lengths):
    """Softmax over each sequence's valid prefix; padding gets 0
    (ref sequence_softmax_op.cc)."""
    mask = _expand_mask(_valid_mask(lengths, x.shape[1]), x)
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask, x, neg)
    z = z - z.max(axis=1, keepdims=True)
    e = jnp.where(mask, jnp.exp(z), 0)
    return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30)


def sequence_reverse(x, lengths):
    """Reverse the valid prefix of each row, keeping padding in place
    (ref sequence_reverse_op.h)."""
    m = x.shape[1]
    lengths = jnp.asarray(lengths)
    pos = jnp.arange(m)[None, :]
    src = jnp.where(pos < lengths[:, None], lengths[:, None] - 1 - pos, pos)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_slice(x, lengths, offset, length):
    """Per-row slice [offset, offset+length) of the valid prefix; returns
    (sliced [batch, length, ...], new_lengths) (ref sequence_slice_op.h).
    ``length`` must be a static int (XLA shapes); offsets may be traced."""
    length = int(length)
    offset = jnp.asarray(offset)
    if offset.ndim == 0:
        offset = jnp.broadcast_to(offset, (x.shape[0],))
    pos = jnp.arange(length)[None, :] + offset[:, None]
    pos = jnp.clip(pos, 0, x.shape[1] - 1)
    out = jnp.take_along_axis(
        x, pos.reshape(pos.shape + (1,) * (x.ndim - 2)), axis=1)
    new_len = jnp.clip(jnp.asarray(lengths) - offset, 0, length)
    return out, new_len.astype(jnp.int32)


def sequence_expand(x, ref_lengths, max_ref=None):
    """Repeat each row x[i] into ``ref_lengths[i]`` timesteps of a padded
    output [batch, max_ref, ...] (ref sequence_expand_op.h with y's lod as
    the repeat counts); slots >= ref_lengths[i] are 0. ``max_ref`` is the
    static output width — required when ref_lengths is traced."""
    ref_lengths = jnp.asarray(ref_lengths)
    if max_ref is None:
        try:
            max_ref = int(ref_lengths.max())
        except jax.errors.ConcretizationTypeError:
            raise ValueError(
                "sequence_expand requires max_ref when ref_lengths is "
                "traced (static output shape under XLA)") from None
    reps = jnp.arange(int(max_ref))[None, :] < ref_lengths[:, None]
    out = jnp.where(reps.reshape(reps.shape + (1,) * (x.ndim - 1)),
                    x[:, None], 0)
    return out, jnp.minimum(ref_lengths, max_ref).astype(jnp.int32)


def sequence_expand_as(x, ref_lengths, max_ref=None):
    """Alias of sequence_expand for 2-D x (ref sequence_expand_as_op.h)."""
    return sequence_expand(x, ref_lengths, max_ref)


def sequence_enumerate(x, lengths, win_size, pad_value=0):
    """Sliding windows of size win_size per position:
    out[b, t] = x[b, t:t+win] with positions beyond the valid length set
    to pad_value (ref sequence_enumerate_op.h). x is [batch, maxlen] ints."""
    m = x.shape[1]
    lengths = jnp.asarray(lengths)
    idx = jnp.arange(m)[:, None] + jnp.arange(win_size)[None, :]  # [m, win]
    gather = jnp.take(x, jnp.clip(idx, 0, m - 1), axis=1)  # [b, m, win]
    valid = idx[None, :, :] < lengths[:, None, None]
    return jnp.where(valid, gather, pad_value)


def sequence_erase(x, lengths, tokens):
    """Remove every occurrence of ``tokens`` from each sequence, compacting
    left and re-padding with 0; returns (out, new_lengths)
    (ref sequence_erase_op.h). Shapes stay static: out has the same maxlen."""
    tokens = jnp.asarray(tokens).reshape(-1)
    m = x.shape[1]
    valid = _valid_mask(lengths, m)
    keep = valid & ~(x[..., None] == tokens[None, None, :]).any(-1)
    # stable compaction: sort positions by (dropped, original index)
    order = jnp.argsort(jnp.where(keep, 0, 1) * m + jnp.arange(m)[None, :],
                        axis=1)
    gathered = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    return jnp.where(_valid_mask(new_len, m), gathered, 0), new_len


def sequence_concat(xs, lengths_list):
    """Concatenate sequences row-wise: out row b = xs[0][b] ++ xs[1][b] ...
    (ref sequence_concat_op.h). Static maxlen = sum of input maxlens."""
    total = sum(x.shape[1] for x in xs)
    batch = xs[0].shape[0]
    tail = xs[0].shape[2:]
    out = jnp.zeros((batch, total) + tail, dtype=xs[0].dtype)
    pos = jnp.zeros((batch,), dtype=jnp.int32)
    for x, ln in zip(xs, lengths_list):
        ln = jnp.asarray(ln)
        m = x.shape[1]
        dest = pos[:, None] + jnp.arange(m)[None, :]
        valid = _valid_mask(ln, m)
        dest = jnp.where(valid, dest, total)  # out-of-range → dropped
        b_idx = jnp.broadcast_to(jnp.arange(batch)[:, None], dest.shape)
        out = out.at[b_idx, dest].set(x, mode="drop")
        pos = pos + ln.astype(jnp.int32)
    return out, pos


def sequence_unpad(x, lengths):
    """Padded → host RaggedTensor (eager only; dynamic result shape)."""

    from ..framework.ragged import RaggedTensor
    return RaggedTensor.from_padded(np.asarray(x), np.asarray(lengths))


def sequence_conv(x, lengths, weight, context_length, context_start=None,
                  context_stride=1, padding_trainable=False,
                  padding_data=None):
    """Context-window convolution over sequences (ref sequence_conv_op.h):
    each timestep concatenates ``context_length`` neighbouring frames
    (starting at ``context_start``, default -(ctx-1)//2) and matmuls with
    ``weight [context_length*dim, out_dim]``.

    ``context_stride`` must be 1 — the reference op enforces the same
    (sequence_conv_op.cc: "Currently, SequenceConvOp only supports
    contextStride=1"). With ``padding_trainable`` the frames a window
    reaches beyond the sequence boundary come from ``padding_data``
    [up_pad + down_pad, dim] (learned rows, ref
    context_project.h ContextProjectFunctor) instead of zeros: row
    ``context_start + k`` (negative offsets index the up rows, overrun
    past the end indexes the down rows)."""
    if context_stride != 1:
        raise ValueError(
            "sequence_conv supports context_stride=1 only (the "
            "reference enforces the same, sequence_conv_op.cc)")
    if context_start is None:
        context_start = -((context_length - 1) // 2)
    up_pad = max(0, -context_start)
    down_pad = max(0, context_start + context_length - 1)
    if padding_trainable:
        if padding_data is None:
            raise ValueError("padding_trainable=True requires "
                             "padding_data [up_pad + down_pad, dim]")
        padding_data = jnp.asarray(padding_data)
    b, m, d = x.shape
    valid = _valid_mask(lengths, m)
    xz = jnp.where(valid[..., None], x, 0)
    lens = jnp.asarray(lengths)[:, None]  # [b, 1]
    cols = []
    for k in range(context_length):
        shift = context_start + k
        idx = jnp.arange(m) + shift
        # in-sequence test is per ROW: a window can overrun the row's
        # own length even inside the dense buffer
        ok = (idx[None, :] >= 0) & (idx[None, :] < lens)
        col = jnp.take(xz, jnp.clip(idx, 0, m - 1), axis=1)
        col = jnp.where(ok[..., None], col, 0)
        if padding_trainable and shift != 0:
            # ref context_project.h: input index idx < 0 reads learned
            # up row (up_pad + idx); idx >= L reads learned down row
            # (up_pad + idx - L)
            n_rows = padding_data.shape[0]
            below = idx[None, :] < 0  # [1, m]
            over = idx[None, :] >= lens  # [b, m]
            in_row = jnp.arange(m)[None, :] < lens
            pu = padding_data[jnp.clip(up_pad + idx, 0, n_rows - 1)]
            pd_row = jnp.clip(up_pad + (idx[None, :] - lens), 0,
                              n_rows - 1)
            pdv = padding_data[pd_row]  # [b, m, d]
            col = jnp.where((below & in_row)[..., None], pu[None], col)
            col = jnp.where((over & in_row)[..., None], pdv, col)
        cols.append(col)
    im2col = jnp.concatenate(cols, axis=-1)  # [b, m, ctx*d]
    out = im2col.reshape(b * m, -1) @ weight
    out = out.reshape(b, m, -1)
    return jnp.where(valid[..., None], out, 0)


def sequence_reshape(x, lengths, new_dim):
    """Re-bucket each sequence's features into rows of width ``new_dim``
    (ref sequence_reshape_op.h: total elements per sequence preserved,
    len_i * D must divide new_dim). x [batch, maxlen, D] -> out
    [batch, maxlen*D//new_dim, new_dim], new_lengths = lengths*D//new_dim."""
    b, m, d = x.shape[0], x.shape[1], x.shape[2]
    if (m * d) % new_dim != 0:
        raise ValueError(
            f"maxlen*dim {m}*{d} not divisible by new_dim {new_dim}")
    out = jnp.reshape(x, (b, (m * d) // new_dim, new_dim))
    new_len = (jnp.asarray(lengths) * d) // new_dim
    return out, new_len.astype(jnp.int32)


def sequence_scatter(x, index, updates, lengths):
    """Per-row scatter-add of a variable-length update sequence
    (ref sequence_scatter_op.h: out[i][index[i][j]] += updates[i][j] for
    j < lengths[i]). x [batch, D], index [batch, T] ints,
    updates [batch, T], lengths [batch]."""
    mask = _valid_mask(lengths, index.shape[1])
    upd = jnp.where(mask, updates, 0).astype(x.dtype)
    idx = jnp.clip(index, 0, x.shape[1] - 1)
    return jax.vmap(lambda row, ii, uu: row.at[ii].add(uu))(x, idx, upd)
