"""Sequence decoding / segment / misc op family (pure functional).

Reference parity for paddle/fluid/operators/: linear_chain_crf_op.cc,
crf_decoding_op.cc, gather_tree_op.cc, beam_search_op.cc (+
beam_search_decode_op.cc), segment_pool (incubate segment ops),
multiplex_op.cc, mv_op.cc, increment_op.cc, p_norm_op.cc,
frobenius_norm_op.cc, mul_op.cc.

The CRF pair runs as lax.scan recursions over time (one fused XLA loop,
batched over sequences) instead of the reference's per-sequence CPU
kernels; beam search is reshaped to the static-shape dense [batch, beam]
form idiomatic for TPU decoding rather than the reference's LoD-based op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# --- linear-chain CRF ---------------------------------------------------------

def _crf_split_transition(transition):
    """Reference layout (linear_chain_crf_op.cc): row 0 = start weights,
    row 1 = stop weights, rows 2: = [num_tags, num_tags] transitions."""
    return transition[0], transition[1], transition[2:]


def linear_chain_crf(emission, transition, label, length=None):
    """Negative log-likelihood of a linear-chain CRF.

    emission: [N, T, K] unary scores; transition: [K+2, K] (start/stop
    rows first, reference layout); label: [N, T] int; length: [N] valid
    steps (defaults to T). Returns nll [N, 1] = log Z - score(gold).
    """
    start_w, stop_w, trans = _crf_split_transition(transition)
    n, t, k = emission.shape
    label = label.astype(jnp.int32)
    if length is None:
        length = jnp.full((n,), t, jnp.int32)
    steps = jnp.arange(t)
    valid = steps[None, :] < length[:, None]                   # [N, T]

    # --- log partition via forward recursion
    alpha0 = start_w[None, :] + emission[:, 0]                 # [N, K]

    def fwd(alpha, inp):
        emit_t, valid_t = inp                                  # [N,K],[N]
        # logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None]               # [N, K, K]
        new = jax.nn.logsumexp(scores, axis=1) + emit_t
        new = jnp.where(valid_t[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(
        fwd, alpha0,
        (emission[:, 1:].swapaxes(0, 1), valid[:, 1:].swapaxes(0, 1)))
    logz = jax.nn.logsumexp(alpha + stop_w[None, :], axis=1)   # [N]

    # --- gold score
    first_emit = jnp.take_along_axis(
        emission[:, 0], label[:, :1], axis=1)[:, 0]
    gold = start_w[label[:, 0]] + first_emit
    prev_lab = label[:, :-1]
    next_lab = label[:, 1:]
    step_trans = trans[prev_lab, next_lab]                     # [N, T-1]
    step_emit = jnp.take_along_axis(emission[:, 1:],
                                    next_lab[..., None], axis=2)[..., 0]
    gold = gold + jnp.where(valid[:, 1:], step_trans + step_emit,
                            0.0).sum(1)
    last_idx = jnp.maximum(length - 1, 0)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = gold + stop_w[last_lab]

    return (logz - gold)[:, None]


def crf_decoding(emission, transition, length=None):
    """Viterbi decode with the CRF transition layout of linear_chain_crf
    (crf_decoding_op.cc). Returns best path [N, T] (entries past `length`
    are 0)."""
    start_w, stop_w, trans = _crf_split_transition(transition)
    n, t, k = emission.shape
    if length is None:
        length = jnp.full((n,), t, jnp.int32)
    steps = jnp.arange(t)
    valid = steps[None, :] < length[:, None]

    alpha0 = start_w[None, :] + emission[:, 0]

    def fwd(alpha, inp):
        emit_t, valid_t = inp
        scores = alpha[:, :, None] + trans[None]               # [N, K, K]
        best_prev = jnp.argmax(scores, axis=1)                 # [N, K]
        new = jnp.max(scores, axis=1) + emit_t
        new = jnp.where(valid_t[:, None], new, alpha)
        best_prev = jnp.where(valid_t[:, None], best_prev,
                              jnp.arange(k)[None, :])
        return new, best_prev

    alpha, backptrs = jax.lax.scan(
        fwd, alpha0,
        (emission[:, 1:].swapaxes(0, 1), valid[:, 1:].swapaxes(0, 1)))
    # stop contribution applies at each sequence's true last step; since
    # invalid steps copy alpha forward, adding stop_w at the end is exact
    last_tag = jnp.argmax(alpha + stop_w[None, :], axis=1)     # [N]

    def back(tag, ptr_t):
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan over backptrs[t] (maps tag at t+1 -> best tag at t):
    # emitted ys[t] = tag at step t+1; final carry = tag at step 0
    first_tag, later = jax.lax.scan(back, last_tag, backptrs, reverse=True)
    full = jnp.concatenate([first_tag[:, None], later.swapaxes(0, 1)],
                           axis=1)                             # [N, T]
    return jnp.where(valid, full, 0)


# --- beam search -------------------------------------------------------------

def beam_search_step(log_probs, scores, beam_size, end_token=None,
                     finished=None):
    """One dense beam-search expansion (TPU-idiomatic form of
    beam_search_op.cc): log_probs [B, beam, V] for the current step,
    scores [B, beam_in] accumulated (beam_in may be 1 on the first step).
    Returns (next_scores [B, beam_size], parent, token)."""
    b, beam_in, v = log_probs.shape
    cand = scores[:, :, None] + log_probs                      # [B, bin, V]
    if finished is not None:
        if end_token is None:
            raise ValueError(
                "beam_search_step: end_token is required with finished")
        # finished beams only propagate via end_token at no cost
        keep = jnp.full((v,), -jnp.inf, cand.dtype).at[
            int(end_token)].set(0.0)
        cand = jnp.where(finished[:, :, None], scores[:, :, None] + keep,
                         cand)
    flat = cand.reshape(b, beam_in * v)
    top, idx = jax.lax.top_k(flat, beam_size)
    parent = idx // v
    token = idx % v
    return top, parent, token


def gather_tree(ids, parents):
    """Backtrace beam-search output (gather_tree_op.cc): ids/parents
    [T, B, beam] -> full sequences [T, B, beam]."""
    t = ids.shape[0]

    def step(beam_idx, inp):
        ids_t, par_t = inp
        tok = jnp.take_along_axis(ids_t, beam_idx, axis=1)
        prev = jnp.take_along_axis(par_t, beam_idx, axis=1)
        return prev, tok

    init = jnp.tile(jnp.arange(ids.shape[2])[None, :], (ids.shape[1], 1))
    _, toks = jax.lax.scan(step, init, (ids, parents), reverse=True)
    return toks


def beam_search_decode(ids, parents, scores=None):
    """Full decode: backtrace + best-beam selection. Returns
    (sequences [B, T] of the best beam, best_scores [B])."""
    full = gather_tree(ids, parents)                           # [T, B, beam]
    if scores is None:
        best = jnp.zeros((ids.shape[1],), jnp.int32)
        best_scores = None
    else:
        best = jnp.argmax(scores, axis=1)                      # [B]
        best_scores = jnp.max(scores, axis=1)
    seq = jnp.take_along_axis(
        full, best[None, :, None], axis=2)[:, :, 0]            # [T, B]
    return seq.swapaxes(0, 1), best_scores


# --- segment ops (incubate segment_pool) -------------------------------------

def _num_segments(num_segments, op_name):
    if num_segments is None:
        raise ValueError(f"{op_name} requires static num_segments on TPU")
    return int(num_segments)


def segment_sum(x, segment_ids, num_segments=None):
    n = _num_segments(num_segments, "segment_sum")
    return jax.ops.segment_sum(x, segment_ids.astype(jnp.int32), n)


def segment_mean(x, segment_ids, num_segments=None):
    n = _num_segments(num_segments, "segment_mean")
    s = jax.ops.segment_sum(x, segment_ids.astype(jnp.int32), n)
    cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype),
                              segment_ids.astype(jnp.int32), n)
    return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))


def segment_max(x, segment_ids, num_segments=None):
    n = _num_segments(num_segments, "segment_max")
    return jax.ops.segment_max(x, segment_ids.astype(jnp.int32), n)


def segment_min(x, segment_ids, num_segments=None):
    n = _num_segments(num_segments, "segment_min")
    return jax.ops.segment_min(x, segment_ids.astype(jnp.int32), n)


def segment_pool(x, segment_ids, pool_type="SUM", num_segments=None):
    fn = {"SUM": segment_sum, "MEAN": segment_mean, "MAX": segment_max,
          "MIN": segment_min}[pool_type.upper()]
    return fn(x, segment_ids, num_segments)


# --- misc --------------------------------------------------------------------

def multiplex(inputs, index):
    """Row-wise select among candidate tensors (multiplex_op.cc):
    out[i] = inputs[index[i]][i]."""
    stacked = jnp.stack(inputs, axis=0)                        # [M, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    return jnp.take_along_axis(
        stacked, idx[None, :].reshape((1, -1) + (1,) * (stacked.ndim - 2)),
        axis=0)[0]


def mv(x, vec):
    """Matrix-vector product (mv_op.cc)."""
    return x @ vec


def increment(x, value=1.0):
    """x + value for a 1-element tensor (increment_op.cc)."""
    return x + jnp.asarray(value, x.dtype)


def p_norm(x, p=2.0, axis=None, epsilon=1e-12, keepdim=False):
    """p-norm along an axis (p_norm_op.cc); supports inf/-inf/0."""
    if p == float("inf"):
        out = jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    elif p == float("-inf"):
        out = jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    elif p == 0:
        out = jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    else:
        out = jnp.power(
            jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim)
            + epsilon, 1.0 / p)
    return out


def frobenius_norm(x, axis=None, keepdim=False):
    """sqrt(sum(x^2)) over the given axes (frobenius_norm_op.cc)."""
    if axis is not None and not isinstance(axis, int):
        axis = tuple(axis)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    """Legacy fluid mul (mul_op.cc): flatten x to 2-D at x_num_col_dims and
    y at y_num_col_dims, matmul, restore leading dims."""
    x2 = x.reshape((int(np.prod(x.shape[:x_num_col_dims])), -1))
    y2 = y.reshape((int(np.prod(y.shape[:y_num_col_dims])), -1))
    out = x2 @ y2
    return out.reshape(tuple(x.shape[:x_num_col_dims])
                       + tuple(y.shape[y_num_col_dims:]))
