"""Shape/layout manipulation ops (pure functional).

Reference parity: python/paddle/tensor/manipulation.py (reshape, transpose,
concat, split, gather, scatter, squeeze, expand, tile, flip, roll, pad...).
Static shapes only where XLA requires them; the few inherently dynamic ops
(masked_select, nonzero) are provided with an eager escape hatch.
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

_slice = builtins.slice


def reshape(x, shape):
    if isinstance(shape, (int, np.integer)):
        shape = (shape,)
    return jnp.reshape(x, tuple(shape))


def transpose(x, perm):
    return jnp.transpose(x, perm)


def t(input):  # noqa: A002
    x = input
    return jnp.transpose(x)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


def concat(xs, axis=0):
    return jnp.concatenate(list(xs), axis=axis)


def stack(x, axis=0):
    xs = x
    return jnp.stack(list(xs), axis=axis)


def unstack(x, axis=0, num=None):
    n = x.shape[axis] if num is None else num
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


def unbind(input, axis=0):  # noqa: A002 - reference name
    """reference: paddle.unbind(input, axis)."""
    return unstack(input, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sections = list(num_or_sections)
    # Resolve a single -1 entry like the reference's split op.
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    offsets = np.cumsum(sections)[:-1]
    return jnp.split(x, offsets.tolist(), axis=axis)


def chunk(x, chunks, axis=0):
    return jnp.array_split(x, chunks, axis=axis)


def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    start = start_axis % nd if nd else 0
    stop = stop_axis % nd if nd else 0
    shape = (x.shape[:start] + (-1,) + x.shape[stop + 1:]) if nd else (-1,)
    return jnp.reshape(x, shape)


def ravel(x):
    return jnp.ravel(x)


def expand(x, shape):
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


def broadcast_tensors(inputs):
    return list(jnp.broadcast_arrays(*inputs))


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def tile(x, repeat_times):
    return jnp.tile(x, tuple(repeat_times))


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    """N-d pad. ``pad`` is a flat [before0, after0, before1, after1, ...]
    list over trailing dims (reference pad_op semantics when len==2*ndim,
    otherwise pads the spatial dims of an NCHW/NHWC feature map)."""
    pad = list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # Spatial-only pad (e.g. [left,right,top,bottom] on NCHW).
        nsp = len(pad) // 2
        width = [(0, 0)] * nd
        # pad pairs are given innermost-FIRST like the reference's
        # functional.pad: (left, right, top, bottom, front, back) with
        # left/right on the last spatial dim (reference:
        # python/paddle/nn/functional/common.py:1149).
        spatial = list(range(nd - nsp, nd)) if data_format.startswith("NC") \
            else list(range(1, 1 + nsp))
        for i, dim in enumerate(reversed(spatial)):
            width[dim] = (pad[2 * i], pad[2 * i + 1])
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    kwargs = {"constant_values": value} if mode == "constant" else {}
    return jnp.pad(x, width, mode=mode_map[mode], **kwargs)


def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def put_along_axis(x, indices, values, axis, reduce="assign"):  # noqa: A002
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis,
                                  inplace=False)
    dnums = {"add": "add", "multiply": "multiply", "mul": "multiply"}[reduce]
    y = jnp.zeros_like(x) if dnums == "add" else jnp.ones_like(x)
    y = jnp.put_along_axis(y, indices, values, axis=axis, inplace=False)
    return x + y if dnums == "add" else x * y


def gather_nd(x, index):
    """Gather slices by an index tensor whose last dim indexes leading dims
    of x (reference: paddle/fluid/operators/gather_nd_op.cc)."""
    index = jnp.asarray(index)
    idx_depth = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx] if idx_depth <= x.ndim else None


def scatter(x, index, updates, overwrite=True):
    """Row scatter (reference scatter_op: index selects rows of x)."""
    index = jnp.asarray(index)
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # accumulate mode: zero out target rows then add
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(jnp.asarray(index), -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    return scatter_nd_add(jnp.zeros(shape, dtype=updates.dtype), index,
                          updates)


def index_add(x, index, axis, value):
    x_moved = jnp.moveaxis(x, axis, 0)
    v_moved = jnp.moveaxis(jnp.asarray(value), axis, 0)
    out = x_moved.at[index].add(v_moved)
    return jnp.moveaxis(out, 0, axis)


def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


def slice(input, axes, starts, ends):  # noqa: A001,A002
    x = input
    """Static slice (reference slice_op)."""
    idx = [_slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = _slice(s, e)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    idx = [_slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = _slice(s, e, st)
    return x[tuple(idx)]


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    """Dynamic-shape op: eager-only (sizes depend on data). Inside jit use
    jnp.nonzero with a size= hint instead."""
    res = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(r) for r in res)
    return jnp.stack([jnp.asarray(r) for r in res], axis=1)


def masked_select(x, mask):
    """Dynamic-shape op: eager-only."""
    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, dtype=x.dtype), x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64"):
    """Dynamic-shape op: eager-only. ``dtype`` sets the index-output
    dtype (reference: paddle.unique dtype arg)."""
    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        idx_dt = np.dtype(dtype) if str(dtype) != "int64" else np.int64
        return tuple(jnp.asarray(
            r.astype(idx_dt) if i > 0 and r.dtype.kind in "iu" else r)
            for i, r in enumerate(res))
    return jnp.asarray(res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """Dynamic-shape op: eager-only (flattens unless axis given)."""
    arr = np.asarray(x)
    if axis is not None:
        moved = np.moveaxis(arr, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        keep = np.concatenate([[True], (flat[1:] != flat[:-1]).any(axis=1)])
        return jnp.asarray(np.moveaxis(moved[keep], 0, axis))
    arr = arr.ravel()
    keep = np.concatenate([[True], arr[1:] != arr[:-1]]) if arr.size else \
        np.zeros(0, dtype=bool)
    rets = [jnp.asarray(arr[keep])]
    if return_inverse:
        rets.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        rets.append(jnp.asarray(np.diff(np.append(idx, arr.size))))
    return rets[0] if len(rets) == 1 else tuple(rets)


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def cast(x, dtype):
    from ..core.dtype import convert_dtype
    return jnp.asarray(x).astype(convert_dtype(dtype))


def numel(x):
    return jnp.asarray(x.size, dtype=jnp.int32)


def shard_index(input, index_num, nshards, shard_id,  # noqa: A002
                ignore_value=-1):
    x = input
    """Map global ids to shard-local ids (reference shard_index_op, used by
    sharded embedding)."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_complex(x):
    return x[..., 0] + 1j * x[..., 1]


def view(x, shape):
    return jnp.reshape(x, tuple(shape))


def crop(x, shape, offsets=None):
    offsets = offsets or [0] * x.ndim
    idx = tuple(_slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


def reverse(x, axis):
    """Reverse x along the given axis/axes (reference: paddle.reverse,
    fluid/layers/tensor.py:1114)."""
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def shape(x):
    """Shape of x as an int32 tensor (reference: paddle.shape,
    fluid/layers/nn.py:11256 — returns a 1-D tensor, not a list)."""
    return jnp.asarray(jnp.shape(x), dtype=jnp.int32)


def is_empty(x):
    """True iff x has zero elements (reference: paddle.is_empty,
    fluid/layers/control_flow.py:3777)."""
    return jnp.asarray(jnp.size(x) == 0)
