"""Neural-net functional ops (pure functional, jax-native).

Reference parity: python/paddle/nn/functional/ (activation.py, common.py,
conv.py, norm.py, pooling.py, loss.py, input.py) backed by the operator
kernels under paddle/fluid/operators/. Convs/matmuls route to
lax.conv_general_dilated / jnp.matmul so XLA tiles them onto the MXU;
data layout follows the reference's NCHW default with a data_format arg.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key

# --------------------------------------------------------------------------
# activations (reference: python/paddle/nn/functional/activation.py)
# --------------------------------------------------------------------------

def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def prelu(x, weight):
    weight = jnp.asarray(weight)
    if weight.size > 1:  # per-channel on axis 1 (NCHW convention)
        shape = [1] * x.ndim
        shape[1] = weight.size
        weight = weight.reshape(shape)
    return jnp.where(x >= 0, x, weight * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, key=None):
    if training:
        k = key if key is not None else next_key()
        slope = jax.random.uniform(k, x.shape, dtype=x.dtype,
                                   minval=lower, maxval=upper)
    else:
        slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return jax.nn.silu(x)


swish = silu


def mish(x):
    return jax.nn.mish(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def tanhshrink(x):
    return x - jnp.tanh(x)


def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(beta * x > threshold, x,
                     jnp.log1p(jnp.exp(beta * x)) / beta)


def softsign(x):
    return jax.nn.soft_sign(x)


def tanh(x):
    return jnp.tanh(x)


def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    k = key if key is not None else next_key()
    g = jax.random.gumbel(k, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                    inplace=False)
        y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through
    return y


def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


# --------------------------------------------------------------------------
# linear / embedding (reference: nn/functional/common.py, input.py)
# --------------------------------------------------------------------------

def linear(x, weight, bias=None):
    """x @ weight + bias; weight is [in, out] (reference convention)."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def embedding(x, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, training=True, mode="upscale_in_train", axis=None,
            key=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    k = key if key is not None else next_key()
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        mask_shape = tuple(s if i in axes else 1 for i, s in
                           enumerate(x.shape))
    else:
        mask_shape = x.shape
    keep = jax.random.bernoulli(k, 1.0 - p, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", key=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, training, axis=axis, key=key)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", key=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, training, axis=axis, key=key)


def alpha_dropout(x, p=0.5, training=True, key=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    k = key if key is not None else next_key()
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2))) if p < 1 else 0.0
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * label + epsilon * prior_dist
    return (1.0 - epsilon) * label + epsilon / n


# --------------------------------------------------------------------------
# convolution (reference: nn/functional/conv.py, operators/conv_op.cc)
# --------------------------------------------------------------------------

def _conv_dimension_numbers(ndim, channel_last):
    # data_format only changes the input/output layout; the weight stays
    # [out_c, in_c, *k] in the reference (conv_op.cc filter layout), so
    # the rhs spec is OI* either way.
    if ndim == 3:
        return ("NWC", "OIW", "NWC") if channel_last else \
            ("NCW", "OIW", "NCW")
    if ndim == 4:
        return ("NHWC", "OIHW", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "OIDHW", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _conv_padding(padding, nsp, stride, dilation, ksize):
    """Translate reference padding spec (int, list, 'SAME', 'VALID')."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nsp
    padding = list(padding)
    if len(padding) == nsp and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    return [tuple(p) for p in padding]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    nsp = 2
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, _conv_dimension_numbers(4, channel_last))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_norm_tuple(stride, nsp),
        padding=_conv_padding(padding, nsp, stride, dilation,
                              weight.shape[2:]),
        rhs_dilation=_norm_tuple(dilation, nsp),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1, 1] if not channel_last else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    channel_last = data_format == "NLC"
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, _conv_dimension_numbers(3, channel_last))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_norm_tuple(stride, 1),
        padding=_conv_padding(padding, 1, stride, dilation, weight.shape[2:]),
        rhs_dilation=_norm_tuple(dilation, 1),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1] if not channel_last else [1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    channel_last = data_format == "NDHWC"
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, _conv_dimension_numbers(5, channel_last))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_norm_tuple(stride, 3),
        padding=_conv_padding(padding, 3, stride, dilation, weight.shape[2:]),
        rhs_dilation=_norm_tuple(dilation, 3),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1, 1, 1] if not channel_last else [1, 1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def _out_padding_from_size(in_sp, output_size, stride, padding,
                           dilation, ksp, nsp):
    """Derive output_padding from a requested output_size (reference
    conv_transpose output_size arg). Valid range per dim: [0, stride)."""
    st = _norm_tuple(stride, nsp)
    dl = _norm_tuple(dilation, nsp)
    osz = _norm_tuple(output_size, nsp)
    op = []
    for i in range(nsp):
        if isinstance(padding, str):
            # SAME: base out = in*stride; VALID: zero padding
            if padding.upper() == "SAME":
                base = in_sp[i] * st[i]
            else:
                base = (in_sp[i] - 1) * st[i] + dl[i] * (ksp[i] - 1) + 1
        else:
            pd = _norm_tuple(padding, nsp)
            base = (in_sp[i] - 1) * st[i] - 2 * pd[i] + \
                dl[i] * (ksp[i] - 1) + 1
        op.append(int(osz[i]) - base)
    if any(o < 0 or o >= st[i] for i, o in enumerate(op)):
        raise ValueError(
            f"output_size {tuple(int(o) for o in osz)} unreachable from "
            f"input {tuple(in_sp)}: derived output_padding {op} must be "
            f"in [0, stride) per dim (stride {st})")
    return tuple(op)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW"):
    """Transposed conv via gradient-of-conv (reference conv2d_transpose_op).
    weight layout matches the reference: [in, out//groups, kh, kw]."""
    if output_size is not None:
        sp = x.shape[1:3] if data_format == "NHWC" else x.shape[2:4]
        output_padding = _out_padding_from_size(
            sp, output_size, stride, padding, dilation, weight.shape[2:4],
            2)
    channel_last = data_format == "NHWC"
    nsp = 2
    strides = _norm_tuple(stride, nsp)
    dilations = _norm_tuple(dilation, nsp)
    pads = _conv_padding(padding, nsp, stride, dilation, weight.shape[2:])
    if isinstance(pads, str):
        pads = [(0, 0)] * nsp if pads == "VALID" else None
    out_pad = _norm_tuple(output_padding, nsp)
    kh = [(weight.shape[2 + i] - 1) * dilations[i] + 1 for i in range(nsp)]
    trans_pads = [(kh[i] - 1 - pads[i][0],
                   kh[i] - 1 - pads[i][1] + out_pad[i]) for i in range(nsp)]
    # flip spatial dims & swap io: [in, out//g, kh, kw] -> [out//g? ...]
    w = jnp.flip(weight, axis=tuple(range(2, weight.ndim)))
    if groups > 1:
        ci, co_g = weight.shape[0], weight.shape[1]
        w = w.reshape(groups, ci // groups, co_g, *weight.shape[2:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape(groups * co_g, ci // groups, *weight.shape[2:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, _conv_dimension_numbers(4, channel_last))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=trans_pads,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1, 1] if not channel_last else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCL"):
    if output_size is not None:
        sp = (x.shape[1],) if data_format == "NLC" else (x.shape[2],)
        output_padding = _out_padding_from_size(
            sp, output_size, stride, padding, dilation,
            (weight.shape[2],), 1)[0]
    x4 = jnp.expand_dims(x, -1 if data_format == "NCL" else 2)
    w4 = jnp.expand_dims(weight, -1)
    out = conv2d_transpose(
        x4, w4, bias, stride=(_norm_tuple(stride, 1)[0], 1),
        padding=(_norm_tuple(padding, 1)[0], 0) if isinstance(
            padding, (int, list, tuple)) else padding,
        output_padding=(_norm_tuple(output_padding, 1)[0], 0),
        dilation=(_norm_tuple(dilation, 1)[0], 1), groups=groups,
        data_format="NCHW" if data_format == "NCL" else "NHWC")
    return jnp.squeeze(out, -1 if data_format == "NCL" else 2)


# --------------------------------------------------------------------------
# pooling (reference: nn/functional/pooling.py, operators/pool_op.cc)
# --------------------------------------------------------------------------

def _pool(x, init, reduce_fn, ksize, stride, padding, nsp, channel_last,
          ceil_mode=False):
    ksize = _norm_tuple(ksize, nsp)
    stride = _norm_tuple(stride if stride is not None else ksize, nsp)
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        p = _conv_padding(padding, nsp, stride, 1, ksize)
        pads = p
    if channel_last:
        window = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
        if not isinstance(pads, str):
            pads = [(0, 0)] + pads + [(0, 0)]
    else:
        window = (1, 1) + ksize
        strides = (1, 1) + stride
        if not isinstance(pads, str):
            pads = [(0, 0), (0, 0)] + pads
    return jax.lax.reduce_window(x, init, reduce_fn, window, strides, pads), \
        (window, strides, pads)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    channel_last = data_format == "NHWC"
    out, _ = _pool(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                   else jnp.iinfo(x.dtype).min, jax.lax.max, kernel_size,
                   stride, padding, 2, channel_last, ceil_mode)
    out = out.astype(x.dtype)
    if return_mask:
        mask = _max_pool_indices(x, kernel_size, stride, padding,
                                 channel_last)
        return out, mask
    return out


def _max_pool_indices(x, kernel_size, stride, padding, channel_last):
    nsp = x.ndim - 2
    ksize = _norm_tuple(kernel_size, nsp)
    stride_t = _norm_tuple(stride if stride is not None else kernel_size, nsp)
    # Build linear spatial indices then reduce-window an argmax via a packed
    # (value, index) trick: encode index in low bits impossible generically —
    # use patch extraction instead (fine for the index path, which is rare).
    if channel_last:
        x_ncs = jnp.moveaxis(x, -1, 1)
    else:
        x_ncs = x
    n, c = x_ncs.shape[:2]
    spatial = x_ncs.shape[2:]
    lin = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    pads = _conv_padding(padding, nsp, stride_t, 1, ksize)
    if isinstance(pads, str):
        pads = [(0, 0)] * nsp
    xp = jnp.pad(x_ncs, [(0, 0), (0, 0)] + list(pads),
                 constant_values=-jnp.inf)
    lp = jnp.pad(lin, list(pads), constant_values=-1)
    out_sp = tuple((xp.shape[2 + i] - ksize[i]) // stride_t[i] + 1
                   for i in range(nsp))
    patches = []
    lins = []
    for offs in np.ndindex(*ksize):
        sl = tuple(_np_slice(offs[i], out_sp[i], stride_t[i])
                   for i in range(nsp))
        patches.append(xp[(slice(None), slice(None)) + sl])
        lins.append(lp[sl])
    stacked = jnp.stack(patches, axis=-1)
    lin_stacked = jnp.stack(lins, axis=-1)
    arg = jnp.argmax(stacked, axis=-1)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(lin_stacked, stacked.shape), arg[..., None],
        axis=-1)[..., 0]
    if channel_last:
        idx = jnp.moveaxis(idx, 1, -1)
    return idx.astype(jnp.int32)


def _np_slice(start, num, step):
    return slice(start, start + num * step, step)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    channel_last = data_format == "NHWC"
    summed, (window, strides, pads) = _pool(
        x, 0.0, jax.lax.add, kernel_size, stride, padding, 2, channel_last,
        ceil_mode)
    if divisor_override:
        return (summed / divisor_override).astype(x.dtype)
    if exclusive and not isinstance(pads, str):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides, pads)
        return (summed / counts).astype(x.dtype)
    denom = np.prod(_norm_tuple(kernel_size, 2))
    return (summed / denom).astype(x.dtype)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False):
    x4 = jnp.expand_dims(x, -1)
    out = max_pool2d(x4, (_norm_tuple(kernel_size, 1)[0], 1),
                     (_norm_tuple(stride, 1)[0], 1) if stride else None,
                     (_norm_tuple(padding, 1)[0], 0) if isinstance(
                         padding, int) else padding,
                     ceil_mode, return_mask)
    if return_mask:
        return jnp.squeeze(out[0], -1), jnp.squeeze(out[1], -1)
    return jnp.squeeze(out, -1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True):
    x4 = jnp.expand_dims(x, -1)
    out = avg_pool2d(x4, (_norm_tuple(kernel_size, 1)[0], 1),
                     (_norm_tuple(stride, 1)[0], 1) if stride else None,
                     (_norm_tuple(padding, 1)[0], 0) if isinstance(
                         padding, int) else padding,
                     ceil_mode, exclusive)
    return jnp.squeeze(out, -1)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    channel_last = data_format == "NDHWC"
    out, _ = _pool(x, -jnp.inf, jax.lax.max, kernel_size, stride, padding, 3,
                   channel_last, ceil_mode)
    return out.astype(x.dtype)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    channel_last = data_format == "NDHWC"
    summed, (window, strides, pads) = _pool(
        x, 0.0, jax.lax.add, kernel_size, stride, padding, 3, channel_last,
        ceil_mode)
    if divisor_override:
        return (summed / divisor_override).astype(x.dtype)
    if exclusive and not isinstance(pads, str):
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       window, strides, pads)
        return (summed / counts).astype(x.dtype)
    return (summed / np.prod(_norm_tuple(kernel_size, 3))).astype(x.dtype)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    channel_last = data_format == "NHWC"
    out_size = _norm_tuple(output_size, 2)
    sp_axes = (1, 2) if channel_last else (2, 3)
    in_size = tuple(x.shape[a] for a in sp_axes)
    if all(i % o == 0 for i, o in zip(in_size, out_size)):
        k = tuple(i // o for i, o in zip(in_size, out_size))
        return avg_pool2d(x, k, k, 0, data_format=data_format)
    # General case: mean over variable windows via cumulative sums.
    return _adaptive_pool_general(x, out_size, sp_axes, "avg")


def adaptive_max_pool2d(x, output_size, return_mask=False,
                        data_format="NCHW"):
    channel_last = data_format == "NHWC"
    out_size = _norm_tuple(output_size, 2)
    sp_axes = (1, 2) if channel_last else (2, 3)
    in_size = tuple(x.shape[a] for a in sp_axes)
    if all(i % o == 0 for i, o in zip(in_size, out_size)):
        k = tuple(i // o for i, o in zip(in_size, out_size))
        return max_pool2d(x, k, k, 0, return_mask=return_mask,
                          data_format=data_format)
    return _adaptive_pool_general(x, out_size, sp_axes, "max")


def _adaptive_pool_general(x, out_size, sp_axes, mode):
    out = x
    for ax, osz in zip(sp_axes, out_size):
        isz = out.shape[ax]
        starts = (np.arange(osz) * isz) // osz
        ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
        slices = []
        for s, e in zip(starts, ends):
            seg = jnp.take(out, jnp.arange(s, e), axis=ax)
            red = jnp.mean(seg, axis=ax, keepdims=True) if mode == "avg" \
                else jnp.max(seg, axis=ax, keepdims=True)
            slices.append(red)
        out = jnp.concatenate(slices, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size):
    x4 = jnp.expand_dims(x, -1)
    return jnp.squeeze(adaptive_avg_pool2d(x4, (output_size, 1)), -1)


def adaptive_max_pool1d(x, output_size, return_mask=False):
    x4 = jnp.expand_dims(x, -1)
    out = jnp.squeeze(adaptive_max_pool2d(x4, (output_size, 1)), -1)
    if return_mask:
        # divisible case: argmax within each window, offset to input index
        n, c, l = x.shape
        o = int(output_size)
        if l % o == 0:
            k = l // o
            win = x.reshape(n, c, o, k)
            idx = jnp.argmax(win, axis=-1) + jnp.arange(o)[None, None] * k
            return out, idx.astype(jnp.int64)
        raise NotImplementedError(
            "return_mask needs input length divisible by output_size")
    return out


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    out_size = _norm_tuple(output_size, 3)
    sp_axes = (1, 2, 3) if data_format == "NDHWC" else (2, 3, 4)
    return _adaptive_pool_general(x, out_size, sp_axes, "avg")


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    """Adaptive 3-D max pool (reference: nn/functional/pooling.py
    adaptive_max_pool3d, operators/pool_op.cc adaptive path)."""
    out_size = _norm_tuple(output_size, 3)
    sp_axes = (1, 2, 3) if data_format == "NDHWC" else (2, 3, 4)
    in_size = tuple(x.shape[a] for a in sp_axes)
    if all(i % o == 0 for i, o in zip(in_size, out_size)):
        k = tuple(i // o for i, o in zip(in_size, out_size))
        return max_pool3d(x, k, k, 0, data_format=data_format)
    return _adaptive_pool_general(x, out_size, sp_axes, "max")


# --------------------------------------------------------------------------
# normalization (reference: nn/functional/norm.py, operators/*norm_op.cc)
# --------------------------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    out = x * jax.lax.rsqrt(var + epsilon).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW"):
    """Returns (out, new_mean, new_var). The stateful Layer handles updating
    running stats; reference semantics: momentum*old + (1-momentum)*new
    (operators/batch_norm_op.cc)."""
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    if training:
        # E[x^2]-E[x]^2 in ONE traversal: jnp.var re-reads x after the
        # mean pass, and on bf16 ResNet-scale activations the extra
        # HBM passes dominated the train-mode forward (measured 6.2 ms
        # of a 14.7 ms ResNet-50 fwd step before this fusion — XLA
        # fuses these two sibling reductions over xf into one pass).
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
        new_rm = momentum * running_mean + (1.0 - momentum) * mean
        new_rv = momentum * running_var + (1.0 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype), new_rm, new_rv


def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = x.reshape(n, num_groups, c // num_groups, *spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, -1] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out.astype(x.dtype)


def normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    sq = jnp.square(x)
    half = size // 2
    pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    sq_p = jnp.pad(sq, pads)
    window = jnp.stack([sq_p[:, i:i + x.shape[1]] for i in range(size)],
                       axis=0).sum(0)
    out = x / jnp.power(k + alpha * window, beta)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


# --------------------------------------------------------------------------
# attention — jnp reference impl; the Pallas flash kernel lives in
# ops/pallas/flash_attention.py and is picked by scaled_dot_product_attention
# when shapes/backend allow.
# --------------------------------------------------------------------------

# Flash-vs-XLA crossover, measured on v5e (r4): XLA's fused attention
# wins at S<=256, flash wins from S=512 up — confirmed across d=64 and
# d=128, causal and not, by a scanned fwd+bwd sweep (whose per-step
# wall times amortize the tunnel dispatch floor equally into both
# sides, so the winner's true margin is LARGER than the raw ratio) and
# by the floor-subtracted full-model step (BERT-base body: 243 ->
# 216.6 ms/step on flash). At S>=2048 the XLA path can stop compiling
# outright — the S^2 scores no longer fit (PROFILE.json r4_correction).
_FLASH_MIN_SEQ = int(__import__("os").environ.get("PT_FLASH_MIN_SEQ",
                                                  "512"))
# The FOLDED kernel has no transposes, so its crossover sits lower
# than the streaming kernel's: measured v5e b64 h12 d64 fwd+bwd
# scanned — S=256 folded 4.55 vs XLA 5.33 ms/iter (folded wins),
# S=128 folded 3.68 vs XLA 2.95 (XLA wins; grid overhead dominates a
# [128,128] score block)
_FOLDED_MIN_SEQ = int(__import__("os").environ.get(
    "PT_FOLDED_MIN_SEQ", "256"))


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None,
                                 key=None, use_flash=None):
    """q,k,v: [batch, seq, heads, head_dim] (reference layout). Computes in
    fp32 accumulation, returns q.dtype.

    ``use_flash``: None (default) = auto — the FOLDED layout-native
    Pallas kernel from key length >= PT_FOLDED_MIN_SEQ (256) when its
    shape gate admits, else the streaming flash kernel from
    >= PT_FLASH_MIN_SEQ (512); XLA's fused attention wins below those
    measured crossovers. True = a Pallas kernel whenever supported;
    False = never. Both kernels require no mask and no active
    dropout."""
    allowed = use_flash is True or (use_flash is None and
                                    k.shape[1] >= _FLASH_MIN_SEQ)
    folded_allowed = use_flash is True or (
        use_flash is None and k.shape[1] >= _FOLDED_MIN_SEQ)
    # the flash kernel's causal mask is diagonal-aligned: with sq != sk
    # (a concatenated KV cache) it would mask from position 0 instead of
    # offsetting by the cache length — the XLA path below applies the
    # correct k=sk-sq shift, so causal cross-length stays off flash
    if ((allowed or folded_allowed) and attn_mask is None and
            (not is_causal or q.shape[1] == k.shape[1]) and
            (dropout_p == 0.0 or not training)):
        from .pallas.flash_attention import (flash_attention,
                                             flash_attention_supported)
        from .pallas.folded_attention import (folded_attention,
                                              folded_attention_supported)
        if folded_allowed and folded_attention_supported(q.shape, k.shape,
                                                         is_causal):
            # single-K-block shapes (BERT S=512): the layout-native
            # folded kernel reads the projection's [B,S,E] rows via
            # 128-lane column groups — no [B,H,S,D] transpose (r4
            # trace: ~27 ms/step of "data formatting" on the BERT-base
            # body came from those round-trips; an r4 attempt at d-wide
            # column blocks failed because Mosaic rejects 64-lane
            # blocks — the fix is 2 heads per 128-lane group, split by
            # in-kernel lane slices)
            return folded_attention(q, k, v, causal=is_causal,
                                    scale=scale)
        if allowed and flash_attention_supported(q.shape, k.shape):
            # streaming shapes (GPT S>=2048): the transposing BHSD
            # kernel (its own crossover stays at _FLASH_MIN_SEQ); at
            # d=128 the strided no-transpose block DMA measured as a
            # wash (GPT step 254.0 vs 251.7 ms), so the transposes
            # stay on this path
            return flash_attention(q, k, v, causal=is_causal, scale=scale)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    qT = jnp.swapaxes(q, 1, 2)  # [b, h, sq, d]
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qT, kT,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(causal, logits, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
        else:
            logits = logits + attn_mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, dropout_p, training=True, key=key)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vT)
    return jnp.swapaxes(out, 1, 2)


def paged_attention(q, k_pages, v_pages, page_table, seq_lens,
                    k_scale=None, v_scale=None, scale=None,
                    q_offsets=None):
    """Ragged paged attention over a block-paged KV pool (the decode
    analog of scaled_dot_product_attention's kernel selection): the
    Pallas page-walk kernel on TPU when the shape gate admits
    (single-token decode, lane-tiling head groups), the dense-gather
    pure-JAX reference everywhere else — both implement identical
    semantics (ops/pallas/paged_attention.py). q: [B, Sq, H, D];
    pages: [P, page, H, D] float or int8 (+ [P, page, H] scales);
    page_table: [B, max_pages] int32; seq_lens: [B] int32."""
    from .pallas.paged_attention import paged_attention as _impl
    return _impl(q, k_pages, v_pages, page_table, seq_lens,
                 k_scale=k_scale, v_scale=v_scale, scale=scale,
                 q_offsets=q_offsets)


def paged_attention_head_sharded(q, k_pages, v_pages, page_table,
                                 seq_lens, k_scale=None, v_scale=None,
                                 scale=None, q_offsets=None, mesh=None,
                                 axis=None):
    """Tensor-parallel ragged paged attention: q and the KV pools are
    sharded over heads along ``mesh[axis]`` and each device runs the
    standard kernel-selection path on its slice (attention is
    head-local, so there are no collectives and per-head arithmetic is
    bit-identical to the single-device op). ``mesh=None`` builds a
    serving mesh over min(2, device_count) devices — the benchable
    default (tools/op_benchmark.py pending case); the mesh-sharded
    decode engine passes its own."""
    from .pallas.paged_attention import \
        paged_attention_head_sharded as _impl
    if mesh is None:
        import jax as _jax
        mesh = _default_serving_mesh(min(2, _jax.device_count()))
    return _impl(q, k_pages, v_pages, page_table, seq_lens, mesh,
                 axis=axis, k_scale=k_scale, v_scale=v_scale,
                 scale=scale, q_offsets=q_offsets)


def paged_attention_fused(q, k_pages, v_pages, page_table, seq_lens,
                          w, bias=None, k_scale=None, v_scale=None,
                          scale=None, q_offsets=None):
    """Ragged paged attention with the output-projection epilogue
    fused in (r13 decode hot path): the softmax-normalized per-head
    context is head-concatenated and pushed through ``w`` ([H*D,
    E_out], optional ``bias``) inside the SAME kernel/op, returning
    the attention block's output [B, Sq, E_out] — one launch where the
    unfused path runs paged_attention + reshape + linear + bias-add.
    Kernel selection mirrors `paged_attention` (Mosaic fused kernel on
    TPU under the shape/VMEM gate, dense-gather fused reference
    elsewhere, head-sharded under an active serving mesh); both are
    the exact unfused math, so greedy decode stays bit-identical
    (ops/pallas/paged_attention.py)."""
    from .pallas.paged_attention import paged_attention_fused as _impl
    return _impl(q, k_pages, v_pages, page_table, seq_lens, w,
                 bias=bias, k_scale=k_scale, v_scale=v_scale,
                 scale=scale, q_offsets=q_offsets)


def fused_sample(hidden, weight, bias=None, transpose_y=False,
                 top_k=None, tile=2048):
    """Streaming lm_head sampling (r13): tile the logits matmul over
    the vocab dim and keep a running argmax (``top_k=None`` -> greedy
    tokens [B] int32, first-index ties exactly like ``argmax``) or a
    running top-k reservoir (``top_k=k`` -> (values, indices) [B, k]),
    so the [B, vocab] logits tensor is never materialized in HBM.
    ``weight``: [V, D] with ``transpose_y=True`` (tied-embedding
    layout) or [D, V] otherwise (ops/pallas/fused_sample.py — Mosaic
    streaming kernel on TPU, lax.scan reference elsewhere)."""
    from .pallas.fused_sample import fused_sample as _impl
    return _impl(hidden, weight, bias=bias, transpose_y=transpose_y,
                 top_k=top_k, tile=tile)


def paged_page_splice(pool, block, page=0):
    """Prefix-cache restore splice (r15 hierarchical prefix cache):
    write one page's restored content ``block`` ([page, H, D] KV
    block, or [page, H] scale block for int8 pools) into ``pool`` at
    page index ``page`` ([P+1, page, ...]; the same pool layout
    `paged_attention` walks). ``page`` may be a traced scalar, so the
    engine's jitted restore compiles ONCE and splices any page index
    (inference/continuous_batching.py restores evicted spill-tier
    blobs through this — a device_put plus this scatter replaces the
    prefix's whole prefill)."""
    return pool.at[page].set(jnp.asarray(block).astype(pool.dtype))


@functools.lru_cache(maxsize=None)
def _default_serving_mesh(model_parallel: int):
    """Memoized benchable-default mesh for
    :func:`paged_attention_head_sharded` — the op is registered in the
    dispatch registry and callable eagerly in a loop; mesh/device-array
    construction per call would be pure overhead for an identical
    result."""
    from ..distributed.topology import make_serving_mesh
    return make_serving_mesh(model_parallel)


# --------------------------------------------------------------------------
# losses (reference: nn/functional/loss.py, operators/*entropy*, bce, etc.)
# --------------------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _sigmoid_ce(logit, target):
    """Numerically stable elementwise sigmoid cross entropy:
    max(z,0) - z*t + log1p(exp(-|z|)). Shared by the loss families."""
    return (jnp.maximum(logit, 0.0) - logit * target
            + jnp.log1p(jnp.exp(-jnp.abs(logit))))


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    logits = input
    if soft_label:
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.clip(logits, 1e-15, None))
        tgt = label
        if label_smoothing > 0.0:
            n = logits.shape[axis]
            tgt = (1 - label_smoothing) * tgt + label_smoothing / n
        loss = -jnp.sum(tgt * logp, axis=axis)
        return _reduce(loss, reduction)
    label = label.astype(jnp.int32)
    squeeze_label = False
    if label.ndim == logits.ndim:
        label = jnp.squeeze(label, axis=axis)
        squeeze_label = True
    logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
        else jnp.log(jnp.clip(logits, 1e-15, None))
    if label_smoothing > 0.0:
        n = logits.shape[axis]
        nll = -jnp.take_along_axis(logp, label[..., None].astype(jnp.int32),
                                   axis=axis)[..., 0]
        smooth = -jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * nll + label_smoothing * smooth
    else:
        loss = -jnp.take_along_axis(
            logp, jnp.expand_dims(label, axis).astype(jnp.int32),
            axis=axis).squeeze(axis)
    valid = (label != ignore_index)
    if weight is not None:
        w = jnp.take(weight, jnp.clip(label, 0, None), axis=0)
        loss = loss * w
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, w, 0.0))
            return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.maximum(
                denom, 1e-12)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False,
                               numeric_stable_mode=True):
    # numeric_stable_mode accepted for reference parity: the log-softmax
    # formulation here is always the stable path
    sm = jax.nn.softmax(logits, axis=axis)
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, sm
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean"):
    loss = -jnp.take_along_axis(input, label[..., None].astype(jnp.int32),
                                axis=-1 if input.ndim == 2 else 1)
    loss = loss.squeeze(-1 if input.ndim == 2 else 1)
    valid = label != ignore_index
    if weight is not None:
        w = jnp.take(weight, jnp.clip(label, 0, None).astype(jnp.int32))
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.sum(
                jnp.where(valid, w, 0.0))
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    return _reduce(loss, reduction)


def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None,  # noqa: A002
                         reduction="mean"):
    x = jnp.clip(input, 1e-12, 1.0 - 1e-12)
    loss = -(label * jnp.log(x) + (1.0 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1.0 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1.0 - label) * logit + max_val + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean"):  # noqa: A002
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0,  # noqa: A002
                        reduction="mean"):
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0,  # noqa: A002
                         reduction="mean"):
    loss = jnp.where(label == 1.0, input,
                     jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    sim = cosine_similarity(input1, input2, axis=1)
    loss = jnp.where(label == 1, 1.0 - sim,
                     jnp.clip(sim - margin, 0, None))
    return _reduce(loss, reduction)


def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0,
                        eps=1e-6, swap=False, reduction="mean"):
    d_pos = jnp.linalg.norm(anchor - positive + eps, ord=p, axis=-1)
    d_neg = jnp.linalg.norm(anchor - negative + eps, ord=p, axis=-1)
    if swap:
        d_neg = jnp.minimum(d_neg, jnp.linalg.norm(
            positive - negative + eps, ord=p, axis=-1))
    loss = jnp.clip(d_pos - d_neg + margin, 0, None)
    return _reduce(loss, reduction)


def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(
        1 - input + epsilon)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


# --------------------------------------------------------------------------
# vision utils (reference: nn/functional/vision.py, common.py)
# --------------------------------------------------------------------------

def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    nsp = x.ndim - 2
    sp_axes = tuple(range(1, 1 + nsp)) if channel_last else \
        tuple(range(2, 2 + nsp))
    in_size = [x.shape[a] for a in sp_axes]
    if size is None:
        sf = _norm_tuple(scale_factor, nsp)
        size = [int(i * s) for i, s in zip(in_size, sf)]
    else:
        size = list(_norm_tuple(size, nsp))
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic",
              "area": "linear"}[mode]
    new_shape = list(x.shape)
    for a, s in zip(sp_axes, size):
        new_shape[a] = s
    if mode == "nearest":
        # match reference nearest (floor) semantics
        idx = [jnp.floor(jnp.arange(s) * (i / s)).astype(jnp.int32)
               for s, i in zip(size, in_size)]
        out = x
        for a, ix in zip(sp_axes, idx):
            out = jnp.take(out, ix, axis=a)
        return out
    if align_mode == 1 and method == "linear" and not align_corners:
        # reference align_mode=1: asymmetric src = dst/scale (the default
        # jax.image.resize linear path is the align_mode=0 half-pixel
        # map). Manual per-axis lerp with edge-clamped gathers — the
        # reference clamps at the boundary, scale_and_translate zero-pads.
        out = x
        for a, (osz, isz) in zip(sp_axes, zip(size, in_size)):
            src = jnp.arange(osz) * (isz / osz)
            i0 = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, isz - 1)
            i1 = jnp.clip(i0 + 1, 0, isz - 1)
            frac = (src - i0).astype(out.dtype)
            shape = [1] * out.ndim
            shape[a] = osz
            frac = frac.reshape(shape)
            out = (jnp.take(out, i0, axis=a) * (1 - frac) +
                   jnp.take(out, i1, axis=a) * frac)
        return out
    return jax.image.resize(x, new_shape, method=method)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(
        n, h // r, w // r, c * r * r)
    return x


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference unfold_op). x: [N, C, H, W] ->
    [N, C*kh*kw, L]."""
    n, c, h, w = x.shape
    kh, kw = _norm_tuple(kernel_sizes, 2)
    sh, sw = _norm_tuple(strides, 2)
    dh, dw = _norm_tuple(dilations, 2)
    pads = _conv_padding(paddings, 2, (sh, sw), (dh, dw), (kh, kw))
    xp = jnp.pad(x, [(0, 0), (0, 0)] + list(pads))
    oh = (xp.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (xp.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, :, i * dh:i * dh + oh * sh:sh,
                       j * dw:j * dw + ow * sw:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # [N, C, kh*kw, oh, ow]
    return out.reshape(n, c * kh * kw, oh * ow)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """x: [N,C,H,W], grid: [N,Hg,Wg,2] in [-1,1]."""
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * ((w - 1) / 2.0) if align_corners else \
        ((grid[..., 0] + 1.0) * w - 1.0) / 2.0
    gy = (grid[..., 1] + 1.0) * ((h - 1) / 2.0) if align_corners else \
        ((grid[..., 1] + 1.0) * h - 1.0) / 2.0

    def sample_one(img, px, py):
        # img: [C,H,W]; px,py: [Hg,Wg]
        if mode == "nearest":
            ix = jnp.clip(jnp.round(px), 0, w - 1).astype(jnp.int32)
            iy = jnp.clip(jnp.round(py), 0, h - 1).astype(jnp.int32)
            return img[:, iy, ix]
        x0 = jnp.floor(px)
        y0 = jnp.floor(py)
        x1, y1 = x0 + 1, y0 + 1
        wx1 = px - x0
        wy1 = py - y0
        vals = 0.0
        for (xi, wxf) in ((x0, 1.0 - wx1), (x1, wx1)):
            for (yi, wyf) in ((y0, 1.0 - wy1), (y1, wy1)):
                valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
                ix = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                iy = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                v = img[:, iy, ix]
                if padding_mode == "zeros":
                    v = jnp.where(valid[None], v, 0.0)
                vals = vals + v * (wxf * wyf)[None]
        return vals

    return jax.vmap(sample_one)(x, gx, gy)


def affine_grid(theta, out_shape, align_corners=True):
    n, c, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h,w,3]
    return jnp.einsum("nij,hwj->nhwi", theta, base)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    if data_format == "NHWC":
        out = temporal_shift(jnp.transpose(x, (0, 3, 1, 2)), seg_num,
                             shift_ratio)
        return jnp.transpose(out, (0, 2, 3, 1))
    n, c, h, w = x.shape
    nt = n // seg_num
    x5 = x.reshape(nt, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x5[:, 1:, :fold],
                            jnp.zeros_like(x5[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x5[:, :1, fold:2 * fold]),
                             x5[:, :-1, fold:2 * fold]], axis=1)
    mid = x5[:, :, 2 * fold:]
    return jnp.concatenate([left, right, mid], axis=2).reshape(n, c, h, w)


def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.swapaxes(x, 1, 2)
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


def sequence_mask(x, maxlen=None, dtype="int64"):
    lengths = x  # reference name: sequence_mask(x, maxlen, dtype)
    maxlen = int(maxlen) if maxlen is not None else None
    if maxlen is None:
        raise ValueError(
            "sequence_mask requires maxlen under XLA static shapes")
    row = jnp.arange(maxlen)
    return (row[None, :] < jnp.asarray(lengths)[..., None]).astype(
        jnp.dtype(dtype))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im, the inverse of unfold (reference fold_op).
    x: [N, C*kh*kw, L] -> [N, C, H, W]; overlapping patches sum."""
    n = x.shape[0]
    oh_img, ow_img = _norm_tuple(output_sizes, 2)
    kh, kw = _norm_tuple(kernel_sizes, 2)
    sh, sw = _norm_tuple(strides, 2)
    dh, dw = _norm_tuple(dilations, 2)
    pads = _conv_padding(paddings, 2, (sh, sw), (dh, dw), (kh, kw))
    (pt, pb), (pl, pr) = pads
    hp, wp = oh_img + pt + pb, ow_img + pl + pr
    oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
    c = x.shape[1] // (kh * kw)
    cols = x.reshape(n, c, kh * kw, oh, ow)
    out = jnp.zeros((n, c, hp, wp), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + oh * sh:sh,
                         j * dw:j * dw + ow * sw:sw].add(
                cols[:, :, i * kw + j])
    return out[:, :, pt:pt + oh_img, pl:pl + ow_img]


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    """Power-average pooling: (sum |x|^p / 1)^(1/p) over each window."""
    p = float(norm_type)
    powed = jnp.abs(x) ** p
    pooled = avg_pool2d(powed, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, exclusive=False,
                        data_format=data_format)
    k = _norm_tuple(kernel_size, 2)
    return (pooled * (k[0] * k[1])) ** (1.0 / p)


def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0).astype(x.dtype)


def pad3d(x, pad, mode="constant", value=0.0,  # noqa: A002
          data_format="NCDHW"):
    """5-D pad over (D, H, W) of NCDHW/NDHWC (reference pad3d_op).
    pad = [left, right, top, bottom, front, back]."""
    from .manipulation import pad as _pad
    l, r, t, b, f, bk = pad
    if data_format == "NCDHW":
        width = [(0, 0), (0, 0), (f, bk), (t, b), (l, r)]
    else:  # NDHWC
        width = [(0, 0), (f, bk), (t, b), (l, r), (0, 0)]
    flat = [v for w in width for v in w]
    return _pad(x, flat, mode=mode, value=value)


def zeropad2d(x, padding, data_format="NCHW"):
    from .manipulation import pad as _pad
    return _pad(x, list(padding), mode="constant", value=0.0,
                data_format=data_format)


def soft_margin_loss(input, label, reduction="mean"):  # noqa: A002
    """log(1 + exp(-label * input)); label in {-1, 1}. Stable softplus
    form (overflow-free for large margins)."""
    loss = jax.nn.softplus(-label.astype(input.dtype) * input)
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean"):
    y = label.astype(input.dtype)
    loss = -(y * jax.nn.log_sigmoid(input) +
             (1.0 - y) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = loss.mean(axis=-1)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        # evaluate log on a safe argument so the untaken where-branch
        # cannot poison gradients with nan (label==0 is common)
        safe = jnp.where(label > 1.0, label, 1.0)
        stirling = safe * jnp.log(safe) - safe + \
            0.5 * jnp.log(2.0 * jnp.pi * safe)
        loss = loss + jnp.where(label > 1.0, stirling, 0.0)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False,  # noqa: A002
                      epsilon=1e-6, reduction="mean"):
    var = jnp.clip(variance, epsilon, None)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * jnp.pi, input.dtype))
    return _reduce(loss, reduction)


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    """CTR feature normalization from ACCUMULATED batch statistics
    (reference data_norm_op.cc: means = batch_sum / batch_size, scales =
    sqrt(batch_size / batch_square_sum) — batch_square_sum accumulates
    CENTERED squares, so scales is 1/std)."""
    mean = batch_sum / batch_size
    scale = jnp.sqrt(batch_size / jnp.maximum(batch_square_sum, epsilon))
    return (x - mean) * scale
