"""Detection op family (jax-native, static shapes).

Reference parity: paddle/fluid/operators/detection/ (66 files). The
kernels there walk dynamic box lists; here every op is fixed-size with
validity masks so it jits and vmaps: NMS returns ``max_out`` slots plus a
count, matchers return per-column indices. Boxes are ``[x1, y1, x2, y2]``
unless noted.

Implemented subset (the ops the reference's SSD/YOLO/R-CNN configs use):
iou_similarity (iou_similarity_op.h), box_coder (box_coder_op.h),
prior_box (prior_box_op.h), anchor_generator (anchor_generator_op.h),
yolo_box (yolo_box_op.h), nms / multiclass_nms (multiclass_nms_op.cc),
roi_align (roi_align_op.h), roi_pool (roi_pool_op.h), bipartite_match
(bipartite_match_op.cc), box_clip (box_clip_op.h).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np


def box_area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)


def iou_similarity(x, y, box_normalized=True):
    """Pairwise IoU: x [N,4], y [M,4] → [N,M]. ``box_normalized=False``
    treats coordinates as pixel indices: widths/heights get the +1
    offset (ref iou_similarity_op.h IOUSimilarityFunctor norm)."""
    off = 0.0 if box_normalized else 1.0
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]

    def area(b):
        return (jnp.maximum(b[..., 2] - b[..., 0] + off, 0) *
                jnp.maximum(b[..., 3] - b[..., 1] + off, 0))

    union = area(x)[:, None] + area(y)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def box_clip(boxes, im_shape):
    """Clip boxes to [0, h-1] x [0, w-1]; im_shape = (h, w)."""
    h, w = im_shape[0], im_shape[1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode",
              box_normalized=True, axis=0):
    """Encode targets against priors or decode deltas back to boxes
    (ref box_coder_op.h EncodeCenterSize/DecodeCenterSize).

    Decode accepts deltas [P, 4] (one per prior) or [R, C, 4] with
    ``axis`` selecting which dim the priors broadcast along (ref
    box_coder_op.cc:69: axis=0 -> prior j for column j, axis=1 ->
    prior i for row i)."""
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0 or 1, got {axis}")
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), prior_box.dtype)
    else:
        var = jnp.asarray(prior_box_var).reshape(-1, 4)
    if code_type == "encode":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)),
            jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)),
        ], axis=-1)  # [T, P, 4]
        return out / var[None]
    if target_box.ndim == 3:
        # [R, C, 4] deltas; priors broadcast along the non-axis dim
        bpw, bph = pw[None, :], ph[None, :]
        if axis == 1:
            bpw, bph = pw[:, None], ph[:, None]
            pcx_b, pcy_b = pcx[:, None], pcy[:, None]
            # per-prior variances ride the prior (row) axis; a shared
            # [1, 4] variance broadcasts either way
            var_b = var[:, None] if var.shape[0] > 1 else var[None]
        else:
            pcx_b, pcy_b = pcx[None, :], pcy[None, :]
            # priors are the column axis here, which [P, 4] -> [1, P, 4]
            # already aligns with
            var_b = var[None]
        d = target_box * var_b
        w = jnp.exp(d[..., 2]) * bpw
        h = jnp.exp(d[..., 3]) * bph
        cx = d[..., 0] * bpw + pcx_b
        cy = d[..., 1] * bph + pcy_b
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm],
                         axis=-1)
    # decode: target_box [P, 4] deltas (one per prior)
    d = target_box * var
    w = jnp.exp(d[:, 2]) * pw
    h = jnp.exp(d[:, 3]) * ph
    cx = d[:, 0] * pw + pcx
    cy = d[:, 1] * ph + pcy
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


def prior_box(feature_h, feature_w, image_h, image_w, min_sizes,
              max_sizes=(), aspect_ratios=(1.0,), flip=True, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5,
              variances=(0.1, 0.1, 0.2, 0.2), min_max_aspect_ratios_order=False):
    """SSD prior boxes (ref prior_box_op.h): returns
    (boxes [fh, fw, num_priors, 4] normalized xyxy, variances same shape)."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    sw = step_w or image_w / feature_w
    sh = step_h or image_h / feature_h
    cx = (jnp.arange(feature_w) + offset) * sw
    cy = (jnp.arange(feature_h) + offset) * sh
    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if k < len(max_sizes):
                s = np.sqrt(ms * max_sizes[k])
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if k < len(max_sizes):
                s = np.sqrt(ms * max_sizes[k])
                whs.append((s, s))
    wh = jnp.asarray(whs, jnp.float32)  # [np, 2]
    cxg, cyg = jnp.meshgrid(cx, cy)  # [fh, fw]
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # [fh, fw, 1, 2]
    half = wh[None, None] * 0.5
    boxes = jnp.concatenate([c - half, c + half], axis=-1)
    boxes = boxes / jnp.asarray([image_w, image_h, image_w, image_h],
                                jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return boxes, var


def anchor_generator(feature_h, feature_w, anchor_sizes, aspect_ratios,
                     stride, offset=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """RPN anchors (ref anchor_generator_op.h): returns
    (anchors [fh, fw, na, 4] in input-image pixels, variances)."""
    combos = list(itertools.product(aspect_ratios, anchor_sizes))
    wh = []
    for ar, sz in combos:
        area = float(sz) * float(sz)
        w = np.sqrt(area / ar)
        wh.append((w, w * ar))
    wh = jnp.asarray(wh, jnp.float32)
    cx = (jnp.arange(feature_w) + offset) * stride[0]
    cy = (jnp.arange(feature_h) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = wh[None, None] * 0.5
    anchors = jnp.concatenate([c - half, c + half], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return anchors, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode one YOLOv3 head (ref yolo_box_op.h).

    x: [N, na*(5+classes), H, W]; img_size: [N, 2] (h, w).
    With ``iou_aware`` (ref yolo_box_op.h:56 GetIoUIndex /
    yolo_box_op.cc:169), x is [N, na*(6+classes), H, W]: the FIRST na
    channels are per-anchor IoU predictions, and the confidence becomes
    conf^(1-factor) * sigmoid(iou)^factor.
    Returns (boxes [N, na*H*W, 4] xyxy in image pixels,
             scores [N, na*H*W, classes]); boxes with conf < thresh are 0.
    """
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    iou = None
    if iou_aware:
        iou = jax.nn.sigmoid(x[:, :na].astype(jnp.float32))  # [n,na,h,w]
        x = x[:, na:]
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * alpha + beta + grid_y) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    if iou_aware:
        conf = (conf ** (1.0 - iou_aware_factor) *
                iou ** iou_aware_factor)
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]  # [n,na,C,h,w]
    keep = conf >= conf_thresh
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw * 0.5) * img_w
    y1 = (by - bh * 0.5) * img_h
    x2 = (bx + bw * 0.5) * img_w
    y2 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(keep[..., None], boxes, 0.0)     # [n,na,h,w,4]
    probs = jnp.where(keep[:, :, None], probs, 0.0)    # [n,na,C,h,w]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w,
                                                    class_num)
    return boxes, scores


def nms(boxes, scores, iou_threshold=0.5, score_threshold=-jnp.inf,
        max_out=None, eta=1.0, normalized=True):
    """Single-class NMS, fixed-size (jittable): returns
    (indices [max_out] int32, valid [max_out] bool). Greedy suppression
    via fori_loop over score-sorted candidates. ``eta`` < 1 is the
    reference's adaptive-NMS decay (multiclass_nms_op.cc NMSFast: after
    each kept box, threshold *= eta while threshold > 0.5);
    ``normalized=False`` uses pixel-index IoU (+1 w/h offset)."""
    n = boxes.shape[0]
    max_out = n if max_out is None else int(max_out)
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    iou = iou_similarity(b, b, box_normalized=normalized)
    alive0 = s > score_threshold

    def body(j, carry):
        # candidate j (score order) is checked against every earlier
        # KEPT box at the CURRENT threshold — which each keep may have
        # decayed (reference NMSFast: keep, then thr *= eta while
        # thr > 0.5)
        alive, thr = carry
        killed = jnp.any((iou[:, j] > thr) & (jnp.arange(n) < j) &
                         alive)
        alive_j = alive[j] & ~killed
        alive = alive.at[j].set(alive_j)
        thr = jnp.where(alive_j & (thr > 0.5), thr * eta, thr)
        return alive, thr

    alive, _ = jax.lax.fori_loop(
        0, n, body, (alive0, jnp.float32(iou_threshold)))
    rank = jnp.cumsum(alive) - 1
    slot = jnp.where(alive, rank, max_out)
    idx_out = jnp.full((max_out,), -1, jnp.int32)
    idx_out = idx_out.at[jnp.clip(slot, 0, max_out)].set(
        order.astype(jnp.int32), mode="drop")
    valid = jnp.arange(max_out) < alive.sum()
    return idx_out, valid


def multiclass_nms(boxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=100, iou_threshold=0.5, background_label=-1,
                   nms_eta=1.0, normalized=True):
    """Per-class NMS + global keep_top_k (ref multiclass_nms_op.cc), one
    image. boxes [N,4], scores [C,N]. Returns fixed-size
    (out [keep_top_k, 6] rows = (class, score, x1, y1, x2, y2), count);
    empty slots hold -1 class. ``nms_eta``/``normalized`` follow the
    reference NMSFast attrs (adaptive decay / pixel-index IoU)."""
    num_classes, n = scores.shape
    nms_top_k = min(int(nms_top_k), n)

    def per_class(c, cls_scores):
        top_s, top_i = jax.lax.top_k(cls_scores, nms_top_k)
        idx, valid = nms(boxes[top_i], top_s, iou_threshold,
                         score_threshold, max_out=nms_top_k,
                         eta=nms_eta, normalized=normalized)
        sel = jnp.where(idx >= 0, top_i[jnp.clip(idx, 0)], 0)
        return (jnp.full((nms_top_k,), c, jnp.float32),
                jnp.where(valid, top_s[jnp.clip(idx, 0)], -1.0),
                boxes[sel], valid)

    cls_ids = jnp.arange(num_classes)
    cls_out = jax.vmap(per_class)(cls_ids, scores)
    cls_f, sc, bx, valid = (v.reshape(-1, *v.shape[2:]) for v in cls_out)
    if background_label >= 0:
        valid = valid & (cls_f != background_label)
    sc = jnp.where(valid, sc, -jnp.inf)
    k = min(int(keep_top_k), sc.shape[0])
    top_s, top_i = jax.lax.top_k(sc, k)
    count = (top_s > -jnp.inf).sum()
    ok = top_s > -jnp.inf
    out = jnp.concatenate([
        jnp.where(ok, cls_f[top_i], -1.0)[:, None],
        jnp.where(ok, top_s, 0.0)[:, None],
        jnp.where(ok[:, None], bx[top_i], 0.0)], axis=1)
    return out, count.astype(jnp.int32)


def roi_align(x, rois, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=False):
    """ROIAlign (ref roi_align_op.h): x [C,H,W] single image,
    rois [R,4] in input-image coords → [R, C, oh, ow].

    Deviation from the reference: with sampling_ratio<=0 the reference
    picks ceil(roi_size/output_size) samples per bin PER ROI — a dynamic
    count XLA cannot express with static shapes — so here it defaults to
    a fixed 2x2 grid (the detectron standard). Pass sampling_ratio
    explicitly for exact parity on known ROI scales."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    c, hh, ww = x.shape
    off = 0.5 if aligned else 0.0
    ratio = 2 if sampling_ratio <= 0 else int(sampling_ratio)

    def one_roi(roi):
        x1 = roi[0] * spatial_scale - off
        y1 = roi[1] * spatial_scale - off
        x2 = roi[2] * spatial_scale - off
        y2 = roi[3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w, bin_h = rw / ow, rh / oh
        # ratio x ratio bilinear samples per bin, averaged
        sy = (jnp.arange(oh)[:, None] * bin_h + y1 +
              (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
        sx = (jnp.arange(ow)[:, None] * bin_w + x1 +
              (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)

        def bilinear(yy, xx):
            # ref semantics: samples beyond [-1, H]/[-1, W] contribute 0;
            # samples in [-1, 0) clamp to the border (roi_align_op.h)
            outside = (yy < -1.0) | (yy > hh) | (xx < -1.0) | (xx > ww)
            yy = jnp.clip(yy, 0.0, hh - 1)
            xx = jnp.clip(xx, 0.0, ww - 1)
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            y1i = jnp.clip(y0 + 1, 0, hh - 1)
            x1i = jnp.clip(x0 + 1, 0, ww - 1)
            ly = yy - y0
            lx = xx - x0
            y0, x0, y1i, x1i = (v.astype(jnp.int32)
                                for v in (y0, x0, y1i, x1i))
            v00 = x[:, y0, x0]
            v01 = x[:, y0, x1i]
            v10 = x[:, y1i, x0]
            v11 = x[:, y1i, x1i]
            val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                   v10 * ly * (1 - lx) + v11 * ly * lx)
            return jnp.where(outside, 0.0, val)

        yy = sy.reshape(-1)  # [oh*ratio]
        xx = sx.reshape(-1)  # [ow*ratio]
        yg = jnp.repeat(yy, xx.shape[0])
        xg = jnp.tile(xx, yy.shape[0])
        vals = bilinear(yg, xg).reshape(c, oh, ratio, ow, ratio)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


def roi_pool(x, rois, output_size, spatial_scale=1.0):
    """ROI max-pool (ref roi_pool_op.h): x [C,H,W], rois [R,4] →
    [R, C, oh, ow]."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    c, hh, ww = x.shape
    ygrid = jnp.arange(hh, dtype=jnp.float32)
    xgrid = jnp.arange(ww, dtype=jnp.float32)

    def one_roi(roi):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bh, bw = rh / oh, rw / ow
        ys = jnp.floor(jnp.arange(oh) * bh + y1)
        ye = jnp.ceil((jnp.arange(oh) + 1) * bh + y1)
        xs = jnp.floor(jnp.arange(ow) * bw + x1)
        xe = jnp.ceil((jnp.arange(ow) + 1) * bw + x1)
        in_y = (ygrid[None, :] >= ys[:, None]) & (ygrid[None, :] <
                                                  ye[:, None])
        in_x = (xgrid[None, :] >= xs[:, None]) & (xgrid[None, :] <
                                                  xe[:, None])
        m = in_y[:, None, :, None] & in_x[None, :, None, :]  # [oh,ow,H,W]
        masked = jnp.where(m[None], x[:, None, None], -jnp.inf)
        out = masked.max(axis=(3, 4))
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty bins → 0

    return jax.vmap(one_roi)(rois)


def bipartite_match(dist, match_type="bipartite", dist_threshold=0.5):
    """Greedy bipartite matching (ref bipartite_match_op.cc): dist
    [N, M] similarity. Returns (match_indices [M] int32 row matched to
    each column, -1 if none, match_dist [M]).

    ``match_type='per_prediction'`` adds the reference's second pass
    (ArgMaxMatch): every column the bipartite pass left unmatched takes
    its argmax row when that similarity >= ``dist_threshold`` (rows may
    be reused by multiple columns in this pass)."""
    if match_type not in ("bipartite", "per_prediction"):
        raise ValueError(
            f"match_type must be 'bipartite' or 'per_prediction', got "
            f"{match_type!r} (bipartite_match_op.cc)")
    n, m = dist.shape
    steps = min(n, m)

    def body(_, carry):
        d, idx, val = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        found = best > -jnp.inf
        idx = jnp.where(found, idx.at[j].set(i.astype(jnp.int32)), idx)
        val = jnp.where(found, val.at[j].set(best), val)
        d = jnp.where(found, d.at[i, :].set(-jnp.inf), d)
        d = jnp.where(found, d.at[:, j].set(-jnp.inf), d)
        return d, idx, val

    idx0 = jnp.full((m,), -1, jnp.int32)
    val0 = jnp.zeros((m,), dist.dtype)
    _, idx, val = jax.lax.fori_loop(
        0, steps, body, (dist.astype(jnp.float32), idx0, val0))
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0).astype(val.dtype)
        take = (idx < 0) & (best_val >= dist_threshold)
        idx = jnp.where(take, best_row, idx)
        val = jnp.where(take, best_val, val)
    return idx, val


# --------------------------------------------------------------------------
# detection training-op tail (reference: operators/detection/ —
# density_prior_box_op, target_assign_op, rpn_target_assign_op,
# generate_proposals_op, matrix_nms_op, distribute/collect_fpn_proposals,
# box_decoder_and_assign_op, mine_hard_examples_op,
# polygon_box_transform_op, locality_aware_nms)
# --------------------------------------------------------------------------

def density_prior_box(feature_h, feature_w, image_h, image_w, fixed_sizes,
                      fixed_ratios=(1.0,), densities=(1,),
                      variances=(0.1, 0.1, 0.2, 0.2), step_w=0.0,
                      step_h=0.0, offset=0.5, clip=False,
                      flatten_to_2d=False):
    """Density prior boxes (density_prior_box_op.h): per (fixed_size,
    density) pair, a density x density grid of shifted anchors per ratio.
    Returns (boxes [fh, fw, P, 4], variances same shape) — or [N, 4] when
    flatten_to_2d."""
    sw = step_w or image_w / feature_w
    sh = step_h or image_h / feature_h
    # density_prior_box_op.h:68-101: the density grid is laid out over one
    # step cell (step_average), not over the fixed_size, and box coords are
    # clamped into [0,1] regardless of the clip attr.
    step_average = int((sw + sh) * 0.5)
    cx = (jnp.arange(feature_w) + offset) * sw
    cy = (jnp.arange(feature_h) + offset) * sh
    boxes = []
    for size, dens in zip(fixed_sizes, densities):
        shift = step_average // dens
        for ratio in fixed_ratios:
            bw = size * float(ratio) ** 0.5
            bh = size / float(ratio) ** 0.5
            origin = -step_average / 2.0 + shift / 2.0
            for di in range(dens):
                for dj in range(dens):
                    ccx = cx[None, :] + origin + dj * shift
                    ccy = cy[:, None] + origin + di * shift
                    ccx = jnp.broadcast_to(ccx, (feature_h, feature_w))
                    ccy = jnp.broadcast_to(ccy, (feature_h, feature_w))
                    boxes.append(jnp.stack(
                        [jnp.maximum((ccx - bw / 2.0) / image_w, 0.0),
                         jnp.maximum((ccy - bh / 2.0) / image_h, 0.0),
                         jnp.minimum((ccx + bw / 2.0) / image_w, 1.0),
                         jnp.minimum((ccy + bh / 2.0) / image_h, 1.0)],
                        axis=-1))
    out = jnp.stack(boxes, axis=2)  # [fh, fw, P, 4]
    if clip:  # ClipFunctor pass: force every coordinate into [0, 1]
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    if flatten_to_2d:
        return out.reshape(-1, 4), var.reshape(-1, 4)
    return out, var


def target_assign(x, match_indices, mismatch_value=0.0):
    """Gather targets by match index with a mismatch fill
    (target_assign_op.h): out[i, j] = x[match_indices[i, j]] when the
    index >= 0, else mismatch_value. Returns (out, out_weight)."""
    x = jnp.asarray(x)
    mi = jnp.asarray(match_indices)
    safe = jnp.clip(mi, 0, x.shape[0] - 1)
    gathered = x[safe]  # [b, np, ...]
    matched = (mi >= 0)
    shape = matched.shape + (1,) * (gathered.ndim - matched.ndim)
    out = jnp.where(matched.reshape(shape), gathered, mismatch_value)
    return out, matched.astype(x.dtype).reshape(shape)


def _assign_anchors(anchors, gts, positive_overlap, negative_overlap,
                    valid=None):
    """Shared anchor-assignment core (rpn_target_assign_op.cc /
    retinanet_target_assign_op.cc): IoU-threshold labels (-1 ignore, 0
    bg, 1 fg) with the every-gt's-best-anchor-is-positive rule.
    ``valid`` masks anchors OUT of assignment entirely (the straddle
    filter runs before assignment in the reference, so a gt's best
    anchor is its best VALID anchor). Returns (labels, best_gt)."""
    n = len(anchors)
    if len(gts) == 0:
        return np.zeros(n, np.int32), np.zeros(n, np.int64)
    ious = np.asarray(iou_similarity(jnp.asarray(anchors),
                                     jnp.asarray(gts)))
    if valid is not None:
        ious = np.where(valid[:, None], ious, -1.0)
    best_gt = ious.argmax(1)
    best_iou = ious.max(1)
    labels = -np.ones(n, np.int32)
    labels[best_iou < negative_overlap] = 0
    labels[best_iou >= positive_overlap] = 1
    labels[ious.argmax(0)] = 1  # every gt's best anchor is positive
    if valid is not None:
        labels[~valid] = -1  # filtered anchors never train
    return labels, best_gt


def _encode_fg_targets(anchors, gts, best_gt, fg):
    """Per-fg-anchor regression targets via box_coder's encode diagonal."""
    if not (len(gts) and len(fg)):
        return np.zeros((0, 4), np.float32)
    enc = np.asarray(box_coder(jnp.asarray(anchors[fg]), None,
                               jnp.asarray(gts[best_gt[fg]]),
                               code_type="encode"))
    # box_coder encode is pairwise [T, P, 4]; the per-anchor target is
    # the (i, i) diagonal
    return enc[np.arange(len(fg)), np.arange(len(fg))] \
        if enc.ndim == 3 else enc


def rpn_target_assign(anchors, gt_boxes, is_crowd=None, im_height=None,
                      im_width=None, rpn_batch_size_per_im=256,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, seed=0,
                      rpn_straddle_thresh=0.0):
    """Sample RPN training anchors (rpn_target_assign_op.cc), host-side
    eager: returns (loc_index, score_index, tgt_bbox, tgt_label,
    bbox_inside_weight) as numpy arrays. ``rpn_straddle_thresh`` >= 0
    drops anchors that straddle the image boundary by more than the
    threshold from sampling entirely (ref FilterStraddleAnchor:
    keep iff x1 >= -thr, y1 >= -thr, x2 < W + thr, y2 < H + thr);
    negative disables the filter (all anchors eligible)."""
    anchors = np.asarray(anchors, np.float32)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    inside = None
    if rpn_straddle_thresh >= 0 and im_height is not None and \
            im_width is not None:
        t = float(rpn_straddle_thresh)
        inside = ((anchors[:, 0] >= -t) & (anchors[:, 1] >= -t) &
                  (anchors[:, 2] < im_width + t) &
                  (anchors[:, 3] < im_height + t))
    # filter BEFORE assignment (ref FilterStraddleAnchor runs first):
    # a border gt whose best anchor straddles must promote its best
    # SURVIVING anchor, not lose its positive entirely
    labels, best_gt = _assign_anchors(anchors, gts, rpn_positive_overlap,
                                      rpn_negative_overlap,
                                      valid=inside)
    rng = np.random.default_rng(seed)
    fg_cap = int(rpn_batch_size_per_im * rpn_fg_fraction)
    fg = np.nonzero(labels == 1)[0]
    if len(fg) > fg_cap:
        drop = rng.choice(fg, len(fg) - fg_cap, replace=False) \
            if use_random else fg[fg_cap:]
        labels[drop] = -1
        fg = np.nonzero(labels == 1)[0]
    bg_cap = rpn_batch_size_per_im - len(fg)
    bg = np.nonzero(labels == 0)[0]
    if len(bg) > bg_cap:
        drop = rng.choice(bg, len(bg) - bg_cap, replace=False) \
            if use_random else bg[bg_cap:]
        labels[drop] = -1
        bg = np.nonzero(labels == 0)[0]
    loc_index = fg
    score_index = np.concatenate([fg, bg])
    tgt = _encode_fg_targets(anchors, gts, best_gt, fg)
    tgt_label = labels[score_index].astype(np.int32)
    inside_w = np.ones_like(tgt, np.float32)
    return loc_index, score_index, tgt, tgt_label, inside_w


def generate_proposals(scores, bbox_deltas, im_shape, anchors,
                       variances=None, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5, min_size=0.1,
                       eta=1.0, pixel_offset=True):
    """RPN proposal generation (generate_proposals_op.cc /
    generate_proposals_v2_op.cc), jittable with fixed output size:
    scores [A], bbox_deltas [A, 4], anchors [A, 4]. Returns
    (rois [post_nms_top_n, 4], roi_scores [post_nms_top_n], valid).
    ``eta`` is the adaptive-NMS decay; ``pixel_offset`` is the v2 attr
    (True = pixel-index +1 convention in decode/clip/size — the v1
    behavior; False = continuous coordinates)."""
    off = 1.0 if pixel_offset else 0.0
    scores = jnp.asarray(scores).reshape(-1)
    deltas = jnp.asarray(bbox_deltas).reshape(-1, 4)
    anchors = jnp.asarray(anchors).reshape(-1, 4)
    k = min(int(pre_nms_top_n), scores.shape[0])
    top, idx = jax.lax.top_k(scores, k)
    boxes = box_coder(anchors[idx], variances, deltas[idx],
                      code_type="decode",
                      box_normalized=not pixel_offset)
    h, w = im_shape[0], im_shape[1]
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, w - off),
                       jnp.clip(boxes[:, 1], 0, h - off),
                       jnp.clip(boxes[:, 2], 0, w - off),
                       jnp.clip(boxes[:, 3], 0, h - off)], axis=1)
    ws = boxes[:, 2] - boxes[:, 0] + off
    hs = boxes[:, 3] - boxes[:, 1] + off
    keep_size = (ws >= min_size) & (hs >= min_size)
    cand_scores = jnp.where(keep_size, top, -jnp.inf)
    sel, valid = nms(boxes, cand_scores, iou_threshold=nms_thresh,
                     max_out=int(post_nms_top_n), eta=eta,
                     normalized=not pixel_offset)
    rois = boxes[sel]
    roi_scores = cand_scores[sel]
    return rois, roi_scores, valid


def matrix_nms(boxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """Matrix (soft-decay) NMS (matrix_nms_op.cc), fully vectorized and
    jittable: boxes [N, 4], scores [C, N]. Returns
    (out [keep_top_k, 6] rows (label, score, x1, y1, x2, y2), valid)."""
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    c, n = scores.shape
    outs = []
    for cls in range(c):
        if cls == background_label:
            continue
        s = scores[cls]
        k = min(int(nms_top_k), n)
        top, idx = jax.lax.top_k(s, k)
        b = boxes[idx]
        ious = jnp.asarray(iou_similarity(b, b))
        ious = jnp.triu(ious, k=1)                      # i<j only
        # reference decay (matrix_nms_op.cc): decay_j = min_{i<j}
        # f(iou_ij) / f(compensate_i), compensate_i = max_{k<i} iou_ki
        compensate = ious.max(axis=0)                   # per index i
        if use_gaussian:
            dmat = jnp.exp(-(ious ** 2 - compensate[:, None] ** 2) /
                           gaussian_sigma)
        else:
            dmat = (1 - ious) / jnp.maximum(1 - compensate[:, None], 1e-9)
        # only i<j entries participate; others must not shrink the min
        tri = jnp.triu(jnp.ones_like(dmat, bool), k=1)
        decay = jnp.where(tri, dmat, 1.0).min(axis=0)
        dec_scores = top * decay
        dec_scores = jnp.where(dec_scores > max(score_threshold,
                                                post_threshold),
                               dec_scores, -jnp.inf)
        outs.append(jnp.concatenate(
            [jnp.full((k, 1), float(cls)), dec_scores[:, None], b],
            axis=1))
    if not outs:  # only the background class present
        return (jnp.zeros((0, 6), boxes.dtype), jnp.zeros((0,), bool))
    allc = jnp.concatenate(outs, axis=0)
    kk = min(int(keep_top_k), allc.shape[0])
    best, bidx = jax.lax.top_k(allc[:, 1], kk)
    out = allc[bidx]
    return out, jnp.isfinite(best)


def distribute_fpn_proposals(rois, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224,
                             pixel_offset=True):
    """Assign RoIs to FPN levels (distribute_fpn_proposals_op.h):
    level = floor(refer_level + log2(sqrt(area)/refer_scale)). Host-side
    eager (per-level counts are dynamic). ``pixel_offset`` matches the
    reference attr: True computes areas with the +1 pixel-index offset
    (the v1 BBoxArea convention), False uses plain widths. Returns
    (rois_per_level list, restore_index)."""
    r = np.asarray(rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(
        (r[:, 2] - r[:, 0] + off) * (r[:, 3] - r[:, 1] + off), 1e-9))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-9))
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, order = [], []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(r[idx])
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros(0, int)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n):
    """Merge per-level proposals by score (collect_fpn_proposals_op.h):
    returns the top post_nms_top_n rois across levels (host-side)."""
    rois = np.concatenate([np.asarray(r, np.float32).reshape(-1, 4)
                           for r in multi_rois], axis=0)
    scores = np.concatenate([np.asarray(s, np.float32).reshape(-1)
                             for s in multi_scores], axis=0)
    order = np.argsort(-scores)[:int(post_nms_top_n)]
    return rois[order], scores[order]


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip_value=4.135):
    """Decode per-class box deltas and pick each row's best-scoring class
    box (box_decoder_and_assign_op.h): target_box [N, C*4],
    box_score [N, C]. Returns (decoded [N, C*4], assigned [N, 4])."""
    pb = jnp.asarray(prior_box)
    tb = jnp.asarray(target_box)
    bs = jnp.asarray(box_score)
    n, c4 = tb.shape
    c = c4 // 4
    decoded = []
    for cls in range(c):
        delta = tb[:, cls * 4:(cls + 1) * 4]
        # reference clamps dw/dh at box_clip_value before exp
        delta = jnp.concatenate(
            [delta[:, :2],
             jnp.minimum(delta[:, 2:], box_clip_value)], axis=1)
        d = box_coder(pb, prior_box_var, delta,
                      code_type="decode", box_normalized=False)
        decoded.append(d)
    dec = jnp.stack(decoded, axis=1)            # [N, C, 4]
    best = jnp.argmax(bs, axis=1)
    assigned = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return dec.reshape(n, c4), assigned


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       mining_type="max_negative", loc_loss=None,
                       neg_dist_threshold=0.5, sample_size=None):
    """OHEM negative mining for SSD (mine_hard_examples_op.cc), host-side:
    keeps all positives plus the highest-loss negatives up to
    neg_pos_ratio * n_pos per row (mining_type='max_negative' ranks by
    cls_loss; 'hard_example' ranks by cls_loss + loc_loss). Returns
    (match_indices — unchanged, since unmatched priors are already -1 and
    positives always stay, matching the reference's UpdatedMatchIndices
    contract — and the per-row selected-negative index lists)."""
    loss = np.asarray(cls_loss, np.float32)
    if mining_type == "hard_example" and loc_loss is not None:
        loss = loss + np.asarray(loc_loss, np.float32)
    mi = np.asarray(match_indices).copy()
    neg_sel = []
    for i in range(mi.shape[0]):
        pos = mi[i] >= 0
        n_neg = int(pos.sum() * neg_pos_ratio) if sample_size is None \
            else int(sample_size)
        neg_idx = np.nonzero(~pos)[0]
        order = neg_idx[np.argsort(-loss[i][neg_idx])]
        keep = set(order[:n_neg].tolist())
        neg_sel.append(sorted(keep))
    return mi, neg_sel


def polygon_box_transform(x):
    """EAST geometry head transform (polygon_box_transform_op.cc):
    channel 2k is offset from the pixel x-coordinate, 2k+1 from y.
    x [N, C, H, W] -> absolute coordinates."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    xs = jnp.arange(w)[None, None, None, :]
    ys = jnp.arange(h)[None, None, :, None]
    chan = jnp.arange(c)[None, :, None, None]
    grid = jnp.where(chan % 2 == 0, xs, ys).astype(x.dtype)
    return 4.0 * grid - x


def locality_aware_nms(boxes, scores, iou_threshold=0.5,
                       score_threshold=0.0, nms_top_k=-1, keep_top_k=-1,
                       nms_eta=1.0, normalized=True,
                       background_label=-1):
    """Locality-aware NMS for quadrangle/box text detection (EAST
    postprocess; ref locality_aware_nms_op.cc): weighted-merge
    consecutive overlapping boxes, then standard NMS. Host-side eager.

    Attr parity with the reference maker: ``nms_top_k`` caps merged
    candidates entering NMS, ``keep_top_k`` caps the output,
    ``nms_eta``/``normalized`` follow NMSFast. ``background_label``
    applies to the reference's [C, N] multi-score layout; this
    single-class entry accepts it for signature parity (class 0 is the
    only class, dropped entirely when background_label == 0)."""
    off = 0.0 if normalized else 1.0
    if background_label == 0:
        return (np.zeros((0, 4), np.float32),
                np.zeros((0,), np.float32))
    b = np.asarray(boxes, np.float32).reshape(-1, 4).copy()
    s = np.asarray(scores, np.float32).reshape(-1).copy()
    keep_b, keep_s = [], []
    for i in range(len(b)):
        if s[i] < score_threshold:
            continue
        if keep_b:
            last = keep_b[-1]
            ix1 = max(last[0], b[i][0]); iy1 = max(last[1], b[i][1])
            ix2 = min(last[2], b[i][2]); iy2 = min(last[3], b[i][3])
            inter = max(ix2 - ix1 + off, 0) * max(iy2 - iy1 + off, 0)
            ua = ((last[2] - last[0] + off) * (last[3] - last[1] + off) +
                  (b[i][2] - b[i][0] + off) *
                  (b[i][3] - b[i][1] + off) - inter)
            if ua > 0 and inter / ua >= iou_threshold:
                wsum = keep_s[-1] + s[i]
                keep_b[-1] = (last * keep_s[-1] + b[i] * s[i]) / wsum
                keep_s[-1] = wsum
                continue
        keep_b.append(b[i])
        keep_s.append(s[i])
    if not keep_b:
        return np.zeros((0, 4), np.float32), np.zeros((0,), np.float32)
    kb = np.stack(keep_b)
    ks = np.asarray(keep_s)
    if nms_top_k > 0 and len(kb) > nms_top_k:
        top = np.argsort(-ks)[:nms_top_k]
        kb, ks = kb[top], ks[top]
    sel, valid = nms(jnp.asarray(kb), jnp.asarray(ks),
                     iou_threshold=iou_threshold, max_out=len(kb),
                     eta=nms_eta, normalized=normalized)
    sel = np.asarray(sel)[np.asarray(valid)]
    if keep_top_k > 0:
        sel = sel[:keep_top_k]
    return kb[sel], ks[sel]


def retinanet_target_assign(anchors, gt_boxes, gt_labels, is_crowd=None,
                            im_height=None, im_width=None,
                            positive_overlap=0.5, negative_overlap=0.4):
    """RetinaNet anchor assignment (retinanet_target_assign_op.cc),
    host-side eager. Unlike rpn_target_assign there is no fg/bg sampling:
    every anchor above/below the overlap thresholds trains, targets carry
    the gt CLASS label, and fg_num (for focal-loss normalization) is
    returned. Returns (loc_index, score_index, tgt_bbox, tgt_label,
    bbox_inside_weight, fg_num)."""
    anchors = np.asarray(anchors, np.float32)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    gtl = np.asarray(gt_labels, np.int32).reshape(-1)
    labels, best_gt = _assign_anchors(anchors, gts, positive_overlap,
                                      negative_overlap)
    fg = np.nonzero(labels == 1)[0]
    bg = np.nonzero(labels == 0)[0]
    loc_index = fg
    score_index = np.concatenate([fg, bg])
    tgt = _encode_fg_targets(anchors, gts, best_gt, fg)
    # class label per trained anchor: gt class for fg, 0 (background) bg
    tgt_label = np.zeros(len(score_index), np.int32)
    if len(gts):
        tgt_label[:len(fg)] = gtl[best_gt[fg]]
    inside_w = np.ones_like(tgt, np.float32)
    # reference counts fg + 1 (rpn_target_assign_op.cc:862
    # "fg_num_data[0] = fg_fake.size() + 1") for focal normalization
    fg_num = np.asarray([len(fg) + 1], np.int32)
    return loc_index, score_index, tgt, tgt_label, inside_w, fg_num


def retinanet_detection_output(bboxes, scores, anchors, im_scale=1.0,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.5):
    """RetinaNet inference head (retinanet_detection_output_op.cc),
    host-side eager. Per FPN level: keep anchors whose best class score
    clears score_threshold (top nms_top_k), decode against that level's
    anchors; merged candidates go through per-class NMS; top keep_top_k
    overall are returned as [N, 6] (label, score, x0, y0, x1, y1).
    ``bboxes``/``scores``/``anchors`` are lists with one entry per level:
    deltas [A_l, 4], class probs [A_l, C], anchors [A_l, 4]."""
    cands_box, cands_score = [], []
    for deltas, probs, anc in zip(bboxes, scores, anchors):
        deltas = np.asarray(deltas, np.float32)
        probs = np.asarray(probs, np.float32)
        anc = np.asarray(anc, np.float32)
        best = probs.max(1)
        keep = np.nonzero(best > score_threshold)[0]
        if len(keep) > nms_top_k:
            keep = keep[np.argsort(-best[keep])[:nms_top_k]]
        if not len(keep):
            continue
        dec = np.asarray(box_coder(jnp.asarray(anc[keep]), None,
                                   jnp.asarray(deltas[keep]),
                                   code_type="decode"))
        cands_box.append(dec / im_scale)
        cands_score.append(probs[keep])
    if not cands_box:
        return np.zeros((0, 6), np.float32)
    boxes_all = np.concatenate(cands_box)       # [M, 4]
    scores_all = np.concatenate(cands_score)    # [M, C]
    out = []
    for c in range(scores_all.shape[1]):
        sc = scores_all[:, c]
        keep = np.nonzero(sc > score_threshold)[0]
        if not len(keep):
            continue
        idx, valid = nms(jnp.asarray(boxes_all[keep]),
                         jnp.asarray(sc[keep]),
                         iou_threshold=nms_threshold)
        kept = keep[np.asarray(idx)[np.asarray(valid)]]
        for i in kept:
            out.append([c + 1, sc[i], *boxes_all[i]])
    if not out:
        return np.zeros((0, 6), np.float32)
    out = np.asarray(out, np.float32)
    if len(out) > keep_top_k:
        out = out[np.argsort(-out[:, 1])[:keep_top_k]]
    return out


def generate_proposal_labels(rpn_rois, gt_classes, gt_boxes,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0, num_classes=81,
                             use_random=True, seed=0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2)):
    """Fast R-CNN training-label sampling
    (generate_proposal_labels_op.cc SampleRoisForOneImage), host-side
    eager: sample fg rois (IoU >= fg_thresh, capped at
    batch_size_per_im * fg_fraction) and bg rois (bg_thresh_lo <= IoU <
    bg_thresh_hi) against the ground truth. Returns (rois, labels,
    bbox_targets, bbox_inside_weights, bbox_outside_weights)."""
    rois = np.asarray(rpn_rois, np.float32).reshape(-1, 4)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    gtc = np.asarray(gt_classes, np.int32).reshape(-1)
    # gt boxes join the candidate pool (reference appends them)
    cand = np.concatenate([rois, gts]) if len(gts) else rois
    rng = np.random.default_rng(seed)
    if len(gts):
        ious = np.asarray(iou_similarity(jnp.asarray(cand),
                                         jnp.asarray(gts)))
        best_gt = ious.argmax(1)
        best_iou = ious.max(1)
    else:
        best_gt = np.zeros(len(cand), np.int64)
        best_iou = np.zeros(len(cand), np.float32)
    fg = np.nonzero(best_iou >= fg_thresh)[0]
    bg = np.nonzero((best_iou >= bg_thresh_lo)
                    & (best_iou < bg_thresh_hi))[0]
    fg_cap = int(batch_size_per_im * fg_fraction)
    if len(fg) > fg_cap:
        fg = rng.choice(fg, fg_cap, replace=False) if use_random \
            else fg[:fg_cap]
    bg_cap = batch_size_per_im - len(fg)
    if len(bg) > bg_cap:
        bg = rng.choice(bg, bg_cap, replace=False) if use_random \
            else bg[:bg_cap]
    keep = np.concatenate([fg, bg]).astype(np.int64)
    out_rois = cand[keep]
    labels = np.zeros(len(keep), np.int32)
    if len(gts):
        labels[:len(fg)] = gtc[best_gt[fg]]
    # per-class box targets (reference expand_bbox_targets layout)
    tgt = np.zeros((len(keep), 4 * num_classes), np.float32)
    inside = np.zeros_like(tgt)
    if len(gts) and len(fg):
        enc = np.asarray(box_coder(jnp.asarray(cand[fg]), None,
                                   jnp.asarray(gts[best_gt[fg]]),
                                   code_type="encode"))
        enc = enc[np.arange(len(fg)), np.arange(len(fg))] \
            if enc.ndim == 3 else enc
        enc = enc / np.asarray(bbox_reg_weights, np.float32)
        for i, c in enumerate(labels[:len(fg)]):
            tgt[i, 4 * c:4 * c + 4] = enc[i]
            inside[i, 4 * c:4 * c + 4] = 1.0
    outside = (inside > 0).astype(np.float32)
    return out_rois, labels, tgt, inside, outside


def generate_mask_labels(im_h, im_w, gt_classes, gt_segms, rois,
                         roi_labels, num_classes=81, resolution=14):
    """Mask R-CNN mask-target rasterization
    (generate_mask_labels_op.cc), host-side eager: for each positive roi,
    rasterize its matched instance's polygon into a resolution x
    resolution binary grid (the reference uses COCO poly2mask; PIL
    rasterization here). gt_segms: list of polygons (one flat [x0, y0,
    x1, y1, ...] list per instance). Returns (mask_rois, roi_has_mask,
    mask_int32 [N, num_classes * resolution**2]) where, as in the
    reference's ExpandMaskTarget, every class slot is -1 (ignore) except
    the matched gt class's slot, which holds the binary mask."""
    from PIL import Image, ImageDraw
    rois = np.asarray(rois, np.float32).reshape(-1, 4)
    roi_labels = np.asarray(roi_labels, np.int32).reshape(-1)
    gtc = np.asarray(gt_classes, np.int32).reshape(-1)
    fg = np.nonzero(roi_labels > 0)[0]
    masks, keep_rois = [], []
    # match each fg roi to the gt instance with max IoU of boxes derived
    # from the polygons
    gt_boxes = []
    for poly in gt_segms:
        p = np.asarray(poly, np.float32).reshape(-1, 2)
        gt_boxes.append([p[:, 0].min(), p[:, 1].min(),
                         p[:, 0].max(), p[:, 1].max()])
    gt_boxes = np.asarray(gt_boxes, np.float32) if gt_segms else \
        np.zeros((0, 4), np.float32)
    for i in fg:
        if not len(gt_boxes):
            continue
        ious = np.asarray(iou_similarity(
            jnp.asarray(rois[i:i + 1]), jnp.asarray(gt_boxes)))[0]
        g = int(ious.argmax())
        x0, y0, x1, y1 = rois[i]
        w = max(x1 - x0, 1e-3)
        h = max(y1 - y0, 1e-3)
        poly = np.asarray(gt_segms[g], np.float32).reshape(-1, 2)
        # polygon into roi-local resolution grid
        px = (poly[:, 0] - x0) * resolution / w
        py = (poly[:, 1] - y0) * resolution / h
        img = Image.new("L", (resolution, resolution), 0)
        ImageDraw.Draw(img).polygon(
            list(zip(px.tolist(), py.tolist())), outline=1, fill=1)
        m = np.asarray(img, np.int32)
        # ExpandMaskTarget layout: -1 everywhere, the matched class's
        # slot carries the binary mask
        expanded = np.full(num_classes * resolution * resolution, -1,
                           np.int32)
        c = int(gtc[g])
        lo = c * resolution * resolution
        expanded[lo:lo + resolution * resolution] = m.reshape(-1)
        masks.append(expanded)
        keep_rois.append(rois[i])
    if not masks:
        return (np.zeros((0, 4), np.float32), np.zeros((0,), np.int32),
                np.zeros((0, num_classes * resolution * resolution),
                         np.int32))
    return (np.asarray(keep_rois, np.float32),
            np.ones(len(masks), np.int32),
            np.stack(masks))
