"""Detection op family (jax-native, static shapes).

Reference parity: paddle/fluid/operators/detection/ (66 files). The
kernels there walk dynamic box lists; here every op is fixed-size with
validity masks so it jits and vmaps: NMS returns ``max_out`` slots plus a
count, matchers return per-column indices. Boxes are ``[x1, y1, x2, y2]``
unless noted.

Implemented subset (the ops the reference's SSD/YOLO/R-CNN configs use):
iou_similarity (iou_similarity_op.h), box_coder (box_coder_op.h),
prior_box (prior_box_op.h), anchor_generator (anchor_generator_op.h),
yolo_box (yolo_box_op.h), nms / multiclass_nms (multiclass_nms_op.cc),
roi_align (roi_align_op.h), roi_pool (roi_pool_op.h), bipartite_match
(bipartite_match_op.cc), box_clip (box_clip_op.h).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np


def box_area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)


def iou_similarity(x, y):
    """Pairwise IoU: x [N,4], y [M,4] → [N,M]."""
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(x)[:, None] + box_area(y)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def box_clip(boxes, im_shape):
    """Clip boxes to [0, h-1] x [0, w-1]; im_shape = (h, w)."""
    h, w = im_shape[0], im_shape[1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode",
              box_normalized=True):
    """Encode targets against priors or decode deltas back to boxes
    (ref box_coder_op.h EncodeCenterSize/DecodeCenterSize)."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), prior_box.dtype)
    else:
        var = jnp.asarray(prior_box_var).reshape(-1, 4)
    if code_type == "encode":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)),
            jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)),
        ], axis=-1)  # [T, P, 4]
        return out / var[None]
    # decode: target_box [P, 4] deltas (one per prior)
    d = target_box * var
    w = jnp.exp(d[:, 2]) * pw
    h = jnp.exp(d[:, 3]) * ph
    cx = d[:, 0] * pw + pcx
    cy = d[:, 1] * ph + pcy
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


def prior_box(feature_h, feature_w, image_h, image_w, min_sizes,
              max_sizes=(), aspect_ratios=(1.0,), flip=True, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5,
              variances=(0.1, 0.1, 0.2, 0.2), min_max_aspect_ratios_order=False):
    """SSD prior boxes (ref prior_box_op.h): returns
    (boxes [fh, fw, num_priors, 4] normalized xyxy, variances same shape)."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    sw = step_w or image_w / feature_w
    sh = step_h or image_h / feature_h
    cx = (jnp.arange(feature_w) + offset) * sw
    cy = (jnp.arange(feature_h) + offset) * sh
    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if k < len(max_sizes):
                s = np.sqrt(ms * max_sizes[k])
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if k < len(max_sizes):
                s = np.sqrt(ms * max_sizes[k])
                whs.append((s, s))
    wh = jnp.asarray(whs, jnp.float32)  # [np, 2]
    cxg, cyg = jnp.meshgrid(cx, cy)  # [fh, fw]
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # [fh, fw, 1, 2]
    half = wh[None, None] * 0.5
    boxes = jnp.concatenate([c - half, c + half], axis=-1)
    boxes = boxes / jnp.asarray([image_w, image_h, image_w, image_h],
                                jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return boxes, var


def anchor_generator(feature_h, feature_w, anchor_sizes, aspect_ratios,
                     stride, offset=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """RPN anchors (ref anchor_generator_op.h): returns
    (anchors [fh, fw, na, 4] in input-image pixels, variances)."""
    combos = list(itertools.product(aspect_ratios, anchor_sizes))
    wh = []
    for ar, sz in combos:
        area = float(sz) * float(sz)
        w = np.sqrt(area / ar)
        wh.append((w, w * ar))
    wh = jnp.asarray(wh, jnp.float32)
    cx = (jnp.arange(feature_w) + offset) * stride[0]
    cy = (jnp.arange(feature_h) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = wh[None, None] * 0.5
    anchors = jnp.concatenate([c - half, c + half], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return anchors, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0):
    """Decode one YOLOv3 head (ref yolo_box_op.h).

    x: [N, na*(5+classes), H, W]; img_size: [N, 2] (h, w).
    Returns (boxes [N, na*H*W, 4] xyxy in image pixels,
             scores [N, na*H*W, classes]); boxes with conf < thresh are 0.
    """
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * alpha + beta + grid_y) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]  # [n,na,C,h,w]
    keep = conf >= conf_thresh
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw * 0.5) * img_w
    y1 = (by - bh * 0.5) * img_h
    x2 = (bx + bw * 0.5) * img_w
    y2 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(keep[..., None], boxes, 0.0)     # [n,na,h,w,4]
    probs = jnp.where(keep[:, :, None], probs, 0.0)    # [n,na,C,h,w]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w,
                                                    class_num)
    return boxes, scores


def nms(boxes, scores, iou_threshold=0.5, score_threshold=-jnp.inf,
        max_out=None):
    """Single-class NMS, fixed-size (jittable): returns
    (indices [max_out] int32, valid [max_out] bool). Greedy suppression
    via fori_loop over score-sorted candidates."""
    n = boxes.shape[0]
    max_out = n if max_out is None else int(max_out)
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    iou = iou_similarity(b, b)
    alive0 = s > score_threshold

    def body(i, alive):
        # if candidate i is alive, kill every lower-scored box with
        # IoU > threshold
        kill = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & alive[i]
        return alive & ~kill

    alive = jax.lax.fori_loop(0, n, body, alive0)
    rank = jnp.cumsum(alive) - 1
    slot = jnp.where(alive, rank, max_out)
    idx_out = jnp.full((max_out,), -1, jnp.int32)
    idx_out = idx_out.at[jnp.clip(slot, 0, max_out)].set(
        order.astype(jnp.int32), mode="drop")
    valid = jnp.arange(max_out) < alive.sum()
    return idx_out, valid


def multiclass_nms(boxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=100, iou_threshold=0.5, background_label=-1):
    """Per-class NMS + global keep_top_k (ref multiclass_nms_op.cc), one
    image. boxes [N,4], scores [C,N]. Returns fixed-size
    (out [keep_top_k, 6] rows = (class, score, x1, y1, x2, y2), count);
    empty slots hold -1 class."""
    num_classes, n = scores.shape
    nms_top_k = min(int(nms_top_k), n)

    def per_class(c, cls_scores):
        top_s, top_i = jax.lax.top_k(cls_scores, nms_top_k)
        idx, valid = nms(boxes[top_i], top_s, iou_threshold,
                         score_threshold, max_out=nms_top_k)
        sel = jnp.where(idx >= 0, top_i[jnp.clip(idx, 0)], 0)
        return (jnp.full((nms_top_k,), c, jnp.float32),
                jnp.where(valid, top_s[jnp.clip(idx, 0)], -1.0),
                boxes[sel], valid)

    cls_ids = jnp.arange(num_classes)
    cls_out = jax.vmap(per_class)(cls_ids, scores)
    cls_f, sc, bx, valid = (v.reshape(-1, *v.shape[2:]) for v in cls_out)
    if background_label >= 0:
        valid = valid & (cls_f != background_label)
    sc = jnp.where(valid, sc, -jnp.inf)
    k = min(int(keep_top_k), sc.shape[0])
    top_s, top_i = jax.lax.top_k(sc, k)
    count = (top_s > -jnp.inf).sum()
    ok = top_s > -jnp.inf
    out = jnp.concatenate([
        jnp.where(ok, cls_f[top_i], -1.0)[:, None],
        jnp.where(ok, top_s, 0.0)[:, None],
        jnp.where(ok[:, None], bx[top_i], 0.0)], axis=1)
    return out, count.astype(jnp.int32)


def roi_align(x, rois, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=False):
    """ROIAlign (ref roi_align_op.h): x [C,H,W] single image,
    rois [R,4] in input-image coords → [R, C, oh, ow].

    Deviation from the reference: with sampling_ratio<=0 the reference
    picks ceil(roi_size/output_size) samples per bin PER ROI — a dynamic
    count XLA cannot express with static shapes — so here it defaults to
    a fixed 2x2 grid (the detectron standard). Pass sampling_ratio
    explicitly for exact parity on known ROI scales."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    c, hh, ww = x.shape
    off = 0.5 if aligned else 0.0
    ratio = 2 if sampling_ratio <= 0 else int(sampling_ratio)

    def one_roi(roi):
        x1 = roi[0] * spatial_scale - off
        y1 = roi[1] * spatial_scale - off
        x2 = roi[2] * spatial_scale - off
        y2 = roi[3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w, bin_h = rw / ow, rh / oh
        # ratio x ratio bilinear samples per bin, averaged
        sy = (jnp.arange(oh)[:, None] * bin_h + y1 +
              (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
        sx = (jnp.arange(ow)[:, None] * bin_w + x1 +
              (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)

        def bilinear(yy, xx):
            # ref semantics: samples beyond [-1, H]/[-1, W] contribute 0;
            # samples in [-1, 0) clamp to the border (roi_align_op.h)
            outside = (yy < -1.0) | (yy > hh) | (xx < -1.0) | (xx > ww)
            yy = jnp.clip(yy, 0.0, hh - 1)
            xx = jnp.clip(xx, 0.0, ww - 1)
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            y1i = jnp.clip(y0 + 1, 0, hh - 1)
            x1i = jnp.clip(x0 + 1, 0, ww - 1)
            ly = yy - y0
            lx = xx - x0
            y0, x0, y1i, x1i = (v.astype(jnp.int32)
                                for v in (y0, x0, y1i, x1i))
            v00 = x[:, y0, x0]
            v01 = x[:, y0, x1i]
            v10 = x[:, y1i, x0]
            v11 = x[:, y1i, x1i]
            val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                   v10 * ly * (1 - lx) + v11 * ly * lx)
            return jnp.where(outside, 0.0, val)

        yy = sy.reshape(-1)  # [oh*ratio]
        xx = sx.reshape(-1)  # [ow*ratio]
        yg = jnp.repeat(yy, xx.shape[0])
        xg = jnp.tile(xx, yy.shape[0])
        vals = bilinear(yg, xg).reshape(c, oh, ratio, ow, ratio)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


def roi_pool(x, rois, output_size, spatial_scale=1.0):
    """ROI max-pool (ref roi_pool_op.h): x [C,H,W], rois [R,4] →
    [R, C, oh, ow]."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    c, hh, ww = x.shape
    ygrid = jnp.arange(hh, dtype=jnp.float32)
    xgrid = jnp.arange(ww, dtype=jnp.float32)

    def one_roi(roi):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bh, bw = rh / oh, rw / ow
        ys = jnp.floor(jnp.arange(oh) * bh + y1)
        ye = jnp.ceil((jnp.arange(oh) + 1) * bh + y1)
        xs = jnp.floor(jnp.arange(ow) * bw + x1)
        xe = jnp.ceil((jnp.arange(ow) + 1) * bw + x1)
        in_y = (ygrid[None, :] >= ys[:, None]) & (ygrid[None, :] <
                                                  ye[:, None])
        in_x = (xgrid[None, :] >= xs[:, None]) & (xgrid[None, :] <
                                                  xe[:, None])
        m = in_y[:, None, :, None] & in_x[None, :, None, :]  # [oh,ow,H,W]
        masked = jnp.where(m[None], x[:, None, None], -jnp.inf)
        out = masked.max(axis=(3, 4))
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty bins → 0

    return jax.vmap(one_roi)(rois)


def bipartite_match(dist):
    """Greedy bipartite matching (ref bipartite_match_op.cc with
    match_type='bipartite'): dist [N, M] similarity. Returns
    (match_indices [M] int32 row matched to each column, -1 if none,
    match_dist [M])."""
    n, m = dist.shape
    steps = min(n, m)

    def body(_, carry):
        d, idx, val = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        found = best > -jnp.inf
        idx = jnp.where(found, idx.at[j].set(i.astype(jnp.int32)), idx)
        val = jnp.where(found, val.at[j].set(best), val)
        d = jnp.where(found, d.at[i, :].set(-jnp.inf), d)
        d = jnp.where(found, d.at[:, j].set(-jnp.inf), d)
        return d, idx, val

    idx0 = jnp.full((m,), -1, jnp.int32)
    val0 = jnp.zeros((m,), dist.dtype)
    _, idx, val = jax.lax.fori_loop(
        0, steps, body, (dist.astype(jnp.float32), idx0, val0))
    return idx, val
