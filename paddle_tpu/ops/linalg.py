"""Linear algebra ops (pure functional).

Reference parity: python/paddle/tensor/linalg.py (norm, cholesky, svd, qr,
inv, solve, eigh, matrix_power, pinv, lstsq, triangular_solve, einsum).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple))
                               else None, axis=tuple(axis) if isinstance(
                                   axis, (list, tuple)) else axis,
                               keepdims=keepdim)
    if p == "nuc":
        return jnp.linalg.norm(x, ord="nuc", axis=tuple(axis),
                               keepdims=keepdim)
    if axis is None:
        return jnp.linalg.norm(x.ravel(), ord=p, keepdims=keepdim)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def vector_norm(x, p=2, axis=None, keepdim=False):
    return jnp.linalg.vector_norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.matrix_norm(x, ord=p, keepdims=keepdim)


def dist(x, y, p=2):
    return jnp.linalg.norm((x - y).ravel(), ord=p)


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def lu(x):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32) + 1  # reference uses 1-based pivots


def inverse(x):
    return jnp.linalg.inv(x)


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def eig(x):
    return jnp.linalg.eig(x)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol).astype(jnp.int32)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def multi_dot(xs):
    return jnp.linalg.multi_dot(xs)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def householder_product(x, tau):
    *batch, m, n = x.shape

    def single(xm, tv):
        H = jnp.eye(m, dtype=x.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, x.dtype), jnp.ones(1, x.dtype),
                                 xm[i + 1:, i]])
            H = H @ (jnp.eye(m, dtype=x.dtype) -
                     tv[i] * jnp.outer(v, v))
        return H[:, :n]

    if batch:
        flat_x = x.reshape((-1, m, n))
        flat_t = tau.reshape((-1, tau.shape[-1]))
        out = jax.vmap(single)(flat_x, flat_t)
        return out.reshape(*batch, m, n)
    return single(x, tau)
