"""Fused lm_head sampling: stream vocab tiles through the logits
matmul, never materializing [B, vocab] logits in HBM.

The decode-side twin of models/gpt.py's ``_chunked_lm_loss`` trick
(ROADMAP item 3 / the r13 fused decode hot path): at serving batch
sizes the [B, vocab] logits tensor exists only to be argmax'd (greedy)
or top-k'd, yet the unfused path round-trips it through HBM every
step — ~B * 50k * 4 bytes of write+read per token at GPT vocab. Here
the lm_head matmul is tiled over the vocab dimension and the sampling
reduction rides the tiles: a running (max, argmax) carry for greedy, a
running top-k reservoir for top-k sampling. Only the [B]-sized winner
(or [B, k] reservoir) ever leaves the core.

Two implementations with identical semantics, selected at call time
exactly like `paged_attention`:

- a Mosaic kernel (grid over vocab tiles, carry in VMEM scratch, the
  weight streamed tile-by-tile) for the greedy path on TPU;
- a pure-JAX ``lax.scan`` reference that runs everywhere else (the CPU
  fast lane) and also implements the top-k reservoir.

Greedy tie-breaking matches ``jnp.argmax`` (first index of the max):
the running carry only replaces its best on a STRICT improvement, so
the earliest maximal index survives — the property the fused-vs-
unfused bit-identity pins lean on. Those pins hold on the CPU lane,
where the streaming reference computes the exact unfused dots; the
MOSAIC kernel keeps operands in their storage dtype with f32
accumulation (matching the unfused MXU lowering's operand precision),
but on-chip bit-parity against the unfused programs is CHIP-PENDING
validation, not a claimed contract. Both weight layouts — vocab-major
[V, D] (tied embedding) and feature-major [D, V] (untied
ColumnParallelLinear) — are tiled along their vocab axis NATIVELY;
canonicalizing by transpose would materialize a V*D copy inside every
decode program, more HBM traffic than the logits the fusion avoids.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Vocab tile: 2048 rows x D lanes keeps the streamed weight tile plus
# the [B, tile] logits block well under 1 MB of VMEM at D=2048 bf16
# while amortizing the per-tile matmul issue cost.
DEFAULT_TILE = 2048


def _vocab_dim(transpose_y: bool) -> int:
    """Which weight axis is the vocab: ``transpose_y=True`` is the
    vocab-major [V, D] tied-embedding layout (logits = hidden @ W.T);
    False the feature-major [D, V] untied-head layout (logits =
    hidden @ W). BOTH are tiled along their vocab axis natively — a
    canonicalizing transpose would materialize a full V*D copy inside
    every decode program, more HBM traffic than the [B, V] logits the
    fusion exists to avoid."""
    return 0 if transpose_y else 1


# --------------------------------------------------------------------------
# Pure-JAX streaming reference (CPU fast lane / semantics contract)
# --------------------------------------------------------------------------

def _tile_starts(vocab: int, tile: int):
    """Clamped tile starts covering [0, vocab): the final tile starts
    at vocab - tile when vocab is not a multiple (its leading rows
    re-evaluate the previous tile's tail — the overlap is masked out,
    so no padded weight copy is ever materialized)."""
    n = max(1, -(-vocab // tile))
    return jnp.asarray([min(i * tile, max(0, vocab - tile))
                        for i in range(n)], jnp.int32), \
        jnp.asarray([i * tile for i in range(n)], jnp.int32)


def _scan_tiles(hidden, weight, vdim, bias, tile, body_init, body_step):
    """Shared vocab-tile scan: slices [start:start+tile] along the
    weight's vocab axis ``vdim`` (dynamic_slice, clamped at the edge —
    NO layout-canonicalizing transpose is ever materialized), computes
    the tile logits in the operands' natural dtype (the same promotion
    the unfused matmul applies) and feeds (logits_f32, idx) to
    ``body_step``. Already-covered overlap rows at the clamped edge are
    masked to -inf so every vocab id contributes exactly once."""
    vocab = weight.shape[vdim]
    d = weight.shape[1 - vdim]
    tile = min(tile, vocab)
    starts, fronts = _tile_starts(vocab, tile)

    def step(carry, xs):
        start, front = xs
        if vdim == 0:  # [V, D]: contract dim 1 of both
            wt = jax.lax.dynamic_slice(weight, (start, 0), (tile, d))
            lg = jax.lax.dot_general(
                hidden, wt, (((1,), (1,)), ((), ())))  # [B, tile]
        else:          # [D, V]: contract hidden dim 1 with dim 0
            wt = jax.lax.dynamic_slice(weight, (0, start), (d, tile))
            lg = jax.lax.dot_general(
                hidden, wt, (((1,), (0,)), ((), ())))  # [B, tile]
        idx = start + jnp.arange(tile, dtype=jnp.int32)
        if bias is not None:
            lg = lg + jax.lax.dynamic_slice(bias, (start,), (tile,))
        lg = jnp.where(idx[None, :] >= front, lg.astype(jnp.float32),
                       _NEG_INF)
        return body_step(carry, lg, idx), None

    carry, _ = jax.lax.scan(step, body_init, (starts, fronts))
    return carry


def fused_argmax_reference(hidden, weight, vdim: int, bias=None,
                           tile: int = DEFAULT_TILE):
    """Streaming greedy: argmax of the full logits without the [B, V]
    intermediate; ties resolve to the first index AND NaN contaminates
    exactly like ``jnp.argmax`` (a NaN tile beats any finite carry, an
    earlier NaN beats a later one), so a numerically-blown checkpoint
    produces the SAME tokens fused or unfused — the --no-fused-step
    bisect contract must not misattribute NaN divergence to fusion."""
    b = hidden.shape[0]

    def init():
        return (jnp.full((b,), _NEG_INF, jnp.float32),
                jnp.zeros((b,), jnp.int32))

    def step(carry, lg, idx):
        best_v, best_i = carry
        tmax = jnp.max(lg, axis=1)
        targ = idx[jnp.argmax(lg, axis=1)]  # first-NaN inside the tile
        upd = (tmax > best_v) | (jnp.isnan(tmax) & ~jnp.isnan(best_v))
        return (jnp.where(upd, tmax, best_v),
                jnp.where(upd, targ, best_i))

    _, best_i = _scan_tiles(hidden, weight, vdim, bias, tile, init(),
                            step)
    return best_i.astype(jnp.int32)


def fused_topk_reference(hidden, weight, vdim: int, k: int, bias=None,
                         tile: int = DEFAULT_TILE
                         ) -> Tuple[jax.Array, jax.Array]:
    """Streaming top-k reservoir: returns ``(values [B, k] f32,
    indices [B, k] i32)`` of the k largest logits — the candidate set
    a top-k sampler draws from — again without the [B, V] tensor. The
    reservoir is merged with each tile via one ``lax.top_k`` over
    [carry | tile]."""
    b = hidden.shape[0]
    vocab = weight.shape[vdim]
    k = min(int(k), vocab)

    def init():
        return (jnp.full((b, k), _NEG_INF, jnp.float32),
                jnp.zeros((b, k), jnp.int32))

    def step(carry, lg, idx):
        vals, idxs = carry
        cand_v = jnp.concatenate([vals, lg], axis=1)
        cand_i = jnp.concatenate(
            [idxs, jnp.broadcast_to(idx[None, :], lg.shape)], axis=1)
        top_v, pos = jax.lax.top_k(cand_v, k)
        return top_v, jnp.take_along_axis(cand_i, pos, axis=1)

    vals, idxs = _scan_tiles(hidden, weight, vdim, bias, tile, init(),
                             step)
    return vals, idxs.astype(jnp.int32)


# --------------------------------------------------------------------------
# Mosaic kernel (TPU): greedy streaming argmax over vocab tiles
# --------------------------------------------------------------------------

def _argmax_kernel(h_ref, w_ref, b_ref, o_ref, best_v, best_i, *,
                   tile: int, vocab: int, n_tiles: int, has_bias: bool,
                   vdim: int):
    """Grid step = one vocab tile: tile matmul on the MXU, running
    (max, first-argmax) carry in VMEM scratch, winner written on the
    final step. The trailing partial tile's out-of-range lanes are
    masked to -inf before the reduction; NaN contaminates exactly like
    ``jnp.argmax`` (first NaN index wins, see the reference)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        best_v[...] = jnp.full(best_v.shape, _NEG_INF, best_v.dtype)
        best_i[...] = jnp.zeros(best_i.shape, best_i.dtype)

    # operands stay in their storage dtype (the unfused lm_head matmul
    # feeds bf16 operands to the MXU too); only the accumulation and
    # the running carry are f32, minimizing fused-vs-unfused rounding
    # skew on chip (exact on-chip bit-identity is not claimed — see
    # module docstring)
    if vdim == 0:  # weight tile [tile, D]
        lg = jax.lax.dot_general(
            h_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [B, tile]
    else:          # weight tile [D, tile]
        lg = jax.lax.dot_general(
            h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [B, tile]
    if has_bias:
        lg = lg + b_ref[...].astype(jnp.float32)
    col = i * tile + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    lg = jnp.where(col < vocab, lg, _NEG_INF)
    nan = jnp.isnan(lg)
    tmax = jnp.max(lg, axis=1, keepdims=True)   # [B, 1]
    # first index achieving the tile max (argmax tie-breaking); with a
    # NaN in the tile, jnp.argmax returns the FIRST NaN index instead
    tile_nan = jnp.any(nan, axis=1, keepdims=True)
    cand = jnp.where(lg == tmax, col, jnp.int32(2 ** 30))
    nan_cand = jnp.where(nan, col, jnp.int32(2 ** 30))
    targ = jnp.where(tile_nan,
                     jnp.min(nan_cand, axis=1, keepdims=True),
                     jnp.min(cand, axis=1, keepdims=True))
    upd = (tmax > best_v[...]) | \
        ((tile_nan | jnp.isnan(tmax)) & ~jnp.isnan(best_v[...]))
    best_i[...] = jnp.where(upd, targ, best_i[...])
    best_v[...] = jnp.where(upd, jnp.where(tile_nan, jnp.nan, tmax),
                            best_v[...])

    @pl.when(i == n_tiles - 1)
    def _():
        o_ref[...] = best_i[...].astype(o_ref.dtype)


def _fused_argmax_pallas(hidden, weight, vdim, bias, tile: int):
    b, d = hidden.shape
    vocab = weight.shape[vdim]
    n_tiles = pl.cdiv(vocab, tile)
    has_bias = bias is not None
    brow = (bias.reshape(1, vocab) if has_bias
            else jnp.zeros((1, 1), jnp.float32))
    if vdim == 0:
        w_spec = pl.BlockSpec((tile, d), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    else:
        w_spec = pl.BlockSpec((d, tile), lambda i: (0, i),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_argmax_kernel, tile=tile, vocab=vocab,
                          n_tiles=n_tiles, has_bias=has_bias,
                          vdim=vdim),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),      # hidden
            w_spec,                                     # weight tile
            pl.BlockSpec((1, tile) if has_bias else (1, 1),
                         (lambda i: (0, i)) if has_bias
                         else (lambda i: (0, 0)),
                         memory_space=pltpu.VMEM),      # bias tile
        ],
        out_specs=pl.BlockSpec((b, 1), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((b, 1), jnp.float32),
                        pltpu.VMEM((b, 1), jnp.int32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * b * d * vocab,
            bytes_accessed=vocab * d * weight.dtype.itemsize,
            transcendentals=0),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
        if hasattr(pltpu, "CompilerParams") else
        pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",)),
    )(hidden, weight, brow)
    return out[:, 0]


def fused_sample_supported(hidden_shape, w_shape,
                           backend: Optional[str] = None,
                           transpose_y: bool = True) -> bool:
    """Gate for the Mosaic streaming-argmax kernel: lane-tiling hidden
    width on a TPU backend, either weight layout (everything else —
    CPU, odd widths, top-k — runs the streaming reference, same
    semantics)."""
    from .flash_attention import _FORCE_DEPTH
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon") and _FORCE_DEPTH == 0:
        return False
    b, d = hidden_shape
    return d % 128 == 0 and w_shape[1 - _vocab_dim(transpose_y)] == d


def fused_sample(hidden, weight, bias=None, transpose_y: bool = False,
                 top_k: Optional[int] = None, tile: int = DEFAULT_TILE):
    """Streaming lm_head sampling primitive.

    ``hidden``: [B, D] final hidden states; ``weight``: the lm_head
    weight — [V, D] with ``transpose_y=True`` (tied-embedding layout,
    logits = hidden @ W.T) or [D, V] with ``transpose_y=False``
    (logits = hidden @ W). ``top_k=None`` returns greedy tokens
    ([B] int32, == argmax of the full logits, first-index ties);
    ``top_k=k`` returns the ``(values [B, k], indices [B, k])``
    reservoir of the k largest logits for a sampler to draw from. The
    [B, V] logits tensor is never materialized either way."""
    vdim = _vocab_dim(transpose_y)
    if top_k is not None:
        return fused_topk_reference(hidden, weight, vdim, top_k,
                                    bias=bias, tile=tile)
    eff_tile = min(int(tile), weight.shape[vdim])
    if fused_sample_supported(hidden.shape, weight.shape,
                              transpose_y=transpose_y) \
            and eff_tile % 128 == 0:
        return _fused_argmax_pallas(hidden, weight, vdim, bias,
                                    eff_tile)
    return fused_argmax_reference(hidden, weight, vdim, bias=bias,
                                  tile=tile)
