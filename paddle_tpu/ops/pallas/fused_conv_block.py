"""Fused residual-bottleneck block forward (eval/inference) in Pallas.

The reference's fused-conv-epilogue kernel class
(paddle/fluid/operators/fused/conv_fusion_op.cc:62 conv+bias+activation
(+residual) via cudnnConvolutionBiasActivationForward, placed by the
inference fusion passes together with conv_bn_fuse_pass) — rebuilt as
cross-layer persistent activation blocking, which is what the v5e
roofline actually rewards (PROFILE_RESNET.json: conv fusions run at 92%
of HBM peak, so the only lever is moving FEWER bytes):

one kernel instance computes an ENTIRE image's bottleneck block
    out = relu(conv3(relu(conv2(relu(conv1(x))))) + x)
with every intermediate living in VMEM — at ResNet-50 shapes a full
[H*W, C] activation plane is at most 1.6 MB, so the chain needs ONE
HBM read of x and ONE write of out, where the per-conv XLA schedule
round-trips every intermediate (~4 big passes per block).

The 3x3 conv runs as 9 shifted matmuls over the flattened [H*W, M]
plane: tap (dy, dx) contributes shift_rows(y1, dy*W+dx) @ W2[tap],
with column-edge taps masked (a row shift in flat index wraps across
image rows exactly where x+dx leaves [0, W)). All matmuls accumulate
in f32 on the MXU.

Scope: stride-1 identity bottleneck blocks (13 of ResNet-50's 16),
NHWC, eval mode — BatchNorm folds into conv scale/bias ahead of the
call (inference/fusion.py). TRAIN-mode chaining is mathematically
blocked by exact batch-norm: stats over (N, H, W) must complete before
the normalized output feeds the next conv, so each BN boundary forces
either an HBM round trip or a full re-read of x per BN (measured and
derived in PROFILE_RESNET.json r5 ceiling note).

MEASURED RESULT (v5e b128 eval forward, scan-16 floor-subtracted,
tools/fused_eval_bench.py): the kernel LOSES to XLA's per-conv
schedule — 10.2-12.7 ms fused vs 8.6-9.6 ms eager across variants
(9 shifted matmuls; im2col single-matmul; image packing; stage-1/2
gating). The HBM bytes it saves are real, but XLA's convolutions use
the hardware conv path with years of layout tuning while this kernel
pays VPU shuffles for the im2col and 50%-lane matmuls at M=64 — at
~9 ms the eval forward is close enough to its bandwidth floor that
the VPU overhead dominates the saved traffic. The kernel therefore
ships OFF by default (enable_fused_conv_eval() / PT_FUSED_CONV_EVAL=1
to opt in) as the reference-parity fused-conv-epilogue capability +
a pinned-down negative result, not as the default path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
# older jax spells CompilerParams TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) or \
    pltpu.TPUCompilerParams


def _shift_rows(v, s, hw):
    """rows i of the result read v[i + s]; out-of-range rows are 0."""
    if s == 0:
        return v
    z = jnp.zeros((abs(s), v.shape[1]), v.dtype)
    if s > 0:
        return jnp.concatenate([v[s:], z], axis=0)
    return jnp.concatenate([z, v[:s]], axis=0)


def _block_kernel(x_ref, w1_ref, w2_ref, w3_ref, b1_ref, b2_ref, b3_ref,
                  o_ref, *, h, w, m, c, g):
    """One instance processes ``g`` whole images, stacked on the row
    axis ([g*H*W, C]) so the matmuls stay MXU-sized even at the late
    stages' tiny spatial planes (stage 4: 49 rows/image — per-image
    matmuls measured 0.85x XLA; packed rows win)."""
    hw = h * w
    rows = g * hw
    x = x_ref[0]  # [g*HW, C]
    f32 = jnp.float32
    # conv1 (1x1) + bias + relu
    y1 = jax.lax.dot_general(x, w1_ref[...], (((1,), (0,)), ((), ())),
                             preferred_element_type=f32)
    y1 = jnp.maximum(y1 + b1_ref[...], 0.0).astype(x.dtype)
    # conv2 (3x3, pad 1): in-VMEM im2col (9 shifted copies stacked on
    # lanes) + ONE deep matmul — contraction 9*M keeps the MXU fed
    # where 9 separate M-deep taps ran it at a fraction of peak.
    # Validity of tap (dy, dx) at in-image position p (row index % HW):
    # p + dy*W + dx in [0, HW) exactly captures the y bound (the x
    # bound catches the dx spill across row ends), so the same mask
    # also stops shifts from reading the NEIGHBOURING image in the
    # row-packed layout.
    pos = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) % hw
    col = pos % w
    pieces = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            s = dy * w + dx
            sh = _shift_rows(y1, s, rows)
            valid = (pos + s >= 0) & (pos + s < hw)
            if dx == -1:
                valid = valid & (col != 0)
            elif dx == 1:
                valid = valid & (col != w - 1)
            pieces.append(jnp.where(valid, sh, 0))
    im2col = jnp.concatenate(pieces, axis=1)  # [g*HW, 9*M]
    acc = jax.lax.dot_general(im2col, w2_ref[...],
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=f32)
    y2 = jnp.maximum(acc + b2_ref[...], 0.0).astype(x.dtype)
    # conv3 (1x1) + bias + residual + relu
    y3 = jax.lax.dot_general(y2, w3_ref[...], (((1,), (0,)), ((), ())),
                             preferred_element_type=f32)
    y3 = y3 + b3_ref[...] + x.astype(f32)
    o_ref[0] = jnp.maximum(y3, 0.0).astype(o_ref.dtype)


def _images_per_instance(n, hw):
    """Measured on v5e (b128 eval sweep): packing multiple images per
    instance to widen the late stages' matmuls LOST outright (12.7 vs
    10.2 ms full-model — the im2col masks and lane shuffles grow with
    the packed plane and the VPU, not the MXU, is the binding unit
    here), so instances stay one image."""
    return 1


def fused_bottleneck_eval(x, w1, b1, w2, b2, w3, b3):
    """x [N, H, W, C] NHWC; w1 [C, M], w2 [9*M, M] (taps stacked
    ky-major), w3 [M, C]; biases [1, ·] f32 (BN pre-folded). Returns
    relu(conv3(relu(conv2(relu(conv1(x))))) + x)."""
    n, h, w, c = x.shape
    m = w1.shape[1]
    hw = h * w
    g = _images_per_instance(n, hw)
    xf = x.reshape(n // g, g * hw, c)

    def pinned(shape):
        nd = len(shape)
        return pl.BlockSpec((*shape,), lambda i: (0,) * nd,
                            memory_space=pltpu.VMEM)

    plane = pl.BlockSpec((1, g * hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_block_kernel, h=h, w=w, m=m, c=c, g=g),
        grid=(n // g,),
        in_specs=[plane, pinned(w1.shape), pinned(w2.shape),
                  pinned(w3.shape), pinned(b1.shape), pinned(b2.shape),
                  pinned(b3.shape)],
        out_specs=plane,
        out_shape=jax.ShapeDtypeStruct((n // g, g * hw, c), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * hw * (c * m * 2 + 9 * m * m),
            bytes_accessed=2 * x.size * x.dtype.itemsize,
            transcendentals=0),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
            # stage-1 planes (two [3136, 256] bf16 in/out, double
            # buffered, plus the [3136, 64] chain intermediates) need
            # ~19 MB — above the 16 MB default scoped budget, well
            # under the chip's physical VMEM
            vmem_limit_bytes=64 * 1024 * 1024),
    )(xf, w1, w2, w3, b1, b2, b3)
    return out.reshape(n, h, w, c)


def fold_bn(conv_w, gamma, beta, mean, var, eps):
    """BN -> conv scale/bias fold (the conv_bn_fuse_pass algebra, at
    call time on eval stats): returns (scaled [out_c, in_c, kh, kw]
    weights, bias [out_c] f32)."""
    scale = (gamma / jnp.sqrt(var + eps)).astype(jnp.float32)
    wf = (conv_w.astype(jnp.float32) *
          scale[:, None, None, None]).astype(conv_w.dtype)
    bias = (beta - mean * scale).astype(jnp.float32)
    return wf, bias


def pack_bottleneck(block):
    """Fold the three BNs of a BottleneckBlock and pack its conv
    weights into the kernel's matmul layouts. Returns the 7-tuple of
    fused_bottleneck_eval parameters (w1, b1, w2, b2, w3, b3 minus x).
    Weight layout in this repo is [out_c, in_c, kh, kw] regardless of
    data_format (inference/fusion.py)."""
    def fold(conv, bn):
        return fold_bn(conv.weight.value, bn.weight.value,
                       bn.bias.value, bn._mean.value,
                       bn._variance.value, bn._epsilon)

    w1, b1 = fold(block.conv1, block.bn1)
    w2, b2 = fold(block.conv2, block.bn2)
    w3, b3 = fold(block.conv3, block.bn3)
    m = w1.shape[0]
    w1m = w1[:, :, 0, 0].T  # [C, M]
    # [M_out, M_in, 3, 3] -> taps ky-major [9*M_in, M_out]
    w2m = w2.transpose(2, 3, 1, 0).reshape(9 * m, m)
    w3m = w3[:, :, 0, 0].T  # [M, C]
    return (w1m, b1[None, :], w2m, b2[None, :], w3m, b3[None, :])


import os as _os

_FUSED_EVAL_ENABLED = bool(int(_os.environ.get("PT_FUSED_CONV_EVAL",
                                               "0")))


def enable_fused_conv_eval(enabled: bool = True) -> None:
    """Opt in to routing eval bottleneck blocks through the fused
    kernel (measured slower than XLA on v5e — see module docstring;
    kept for parity with conv_fusion_op and for backends/shapes where
    the trade flips)."""
    global _FUSED_EVAL_ENABLED
    _FUSED_EVAL_ENABLED = bool(enabled)


def fused_bottleneck_supported(block, x_shape, data_format,
                               backend: Optional[str] = None) -> bool:
    """Gate: opted in, stride-1 dilation-1 ungrouped identity
    bottleneck with plain BatchNorm2D norms, NHWC, TPU-family backend,
    plane fits comfortably in VMEM."""
    from ...nn.norm import BatchNorm2D
    from .flash_attention import _FORCE_DEPTH
    if not _FUSED_EVAL_ENABLED:
        return False
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon") and _FORCE_DEPTH == 0:
        return False
    if data_format != "NHWC" or block.downsample is not None:
        return False
    if block.conv2._stride not in (1, (1, 1)):
        return False
    if block.conv2._dilation not in (1, (1, 1)):
        return False
    if getattr(block.conv2, "_groups", 1) != 1:
        return False
    # pack_bottleneck folds _mean/_variance/_epsilon — plain BN only
    if not all(type(bn) is BatchNorm2D
               for bn in (block.bn1, block.bn2, block.bn3)):
        return False
    n, h, w, c = x_shape
    if h * w < 784:
        # stage-3/4 planes (196/49 positions): per-image matmuls are
        # too small for the MXU and packing lost (see
        # _images_per_instance) — XLA keeps those blocks
        return False
    m = block.conv1.weight.shape[0]
    # x + out + y1/y2/acc + weights, double-buffered planes
    vmem = (2 * h * w * c * 2 + h * w * m * (2 * 2 + 4) +
            (c * m * 2 + 9 * m * m) * 2) * 2
    return vmem < 100 * 2 ** 20 and c == 4 * m
