"""Layout-native ("folded") flash attention for single-K-block shapes.

The BERT-shape fix for the [B,S,H,D] -> [B,H,S,D] transpose tax
(PROFILE_BERT.json trace_attribution r4: ~27 ms/step of "data
formatting" around the flash custom-calls — pure overhead created by
the kernel's calling convention, named by the r4 verdict as the #2
perf item). Reference analog: the fused CUDA attention
paddle/fluid/operators/fused/multihead_matmul_op.cu, which likewise
reads the projection's natural [B, S, 3*H*D] layout directly.

Design: q/k/v stay in the projection's natural [B, S, E] layout
(E = H*D; the model-side [B,S,H,D] reshape is a free bitcast). The
grid tiles E into 128-lane column groups — exactly 2 heads at d=64,
1 head at d=128 — so every block DMA is lane-aligned on the native
row-major layout and NO transpose is ever materialized. Heads inside
a group are separated by in-kernel lane slicing (measured: Mosaic
lowers the 64-lane slices fine; the whole fwd+bwd runs ~19% faster
than the transposing BHSD path on the isolated b64 h12 s512 d64
microbench, and the win compounds in the full model where the
transposes also break XLA fusion).

Single-K-block only (sq == sk == one block <= 1024): at these shapes
the whole score matrix fits in VMEM, so
- the forward is a plain softmax (no online-softmax streaming state);
- the backward RECOMPUTES the softmax from q/k and derives
  delta = rowsum(p_hat * dp) in-register — no saved lse, no delta
  prepass, no out residual. Residuals are (q, k, v) alone, in the
  fused single pass dQ/dK/dV kernel.
Longer sequences stay on the streaming BHSD kernels in
flash_attention.py (GPT S>=2048 causal), where online softmax is
actually needed.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
# older jax spells CompilerParams TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) or \
    pltpu.TPUCompilerParams

# whole-S score blocks: [S, S] f32 intermediates in VMEM. 1024 keeps
# the backward's live set (~4 x 4 MB) inside the scoped-vmem budget.
MAX_SINGLE_BLOCK = 1024
_NEG_INF = -1e30


def _heads_per_group(d: int) -> int:
    return 128 // d


def _causal_mask(s):
    q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, d, grp):
    outs = []
    for hh in range(grp):
        sl = slice(hh * d, (hh + 1) * d)
        qh = q_ref[0][:, sl]
        kh = k_ref[0][:, sl]
        vh = v_ref[0][:, sl]
        s = jax.lax.dot_general(qh, kh, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
        acc = jax.lax.dot_general(p.astype(vh.dtype), vh,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        outs.append(acc / l)
    o_ref[0] = jnp.concatenate(outs, axis=1).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref,
                *, scale, causal, d, grp):
    """Fused dQ/dK/dV with in-kernel softmax recompute: p_hat is rebuilt
    from q/k (no saved lse) and delta = rowsum(p_hat * dp) replaces the
    separate rowsum(do * o) prepass — identical by substitution:
    o = p_hat @ v  =>  rowsum(do * o) = rowsum(p_hat * (do @ v^T))."""
    dqs, dks, dvs = [], [], []
    for hh in range(grp):
        sl = slice(hh * d, (hh + 1) * d)
        qh = q_ref[0][:, sl]
        kh = k_ref[0][:, sl]
        vh = v_ref[0][:, sl]
        doh = do_ref[0][:, sl]
        s = jax.lax.dot_general(qh, kh, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
        phat = p / l  # [S, S] f32, normalized
        dp = jax.lax.dot_general(doh, vh, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = jnp.sum(phat * dp, axis=1, keepdims=True)
        ds = phat * (dp - delta) * scale
        dsc = ds.astype(qh.dtype)
        dqs.append(jax.lax.dot_general(
            dsc, kh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))
        dks.append(jax.lax.dot_general(
            dsc, qh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))
        dvs.append(jax.lax.dot_general(
            phat.astype(doh.dtype), doh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))
    dq_ref[0] = jnp.concatenate(dqs, axis=1).astype(dq_ref.dtype)
    dk_ref[0] = jnp.concatenate(dks, axis=1).astype(dk_ref.dtype)
    dv_ref[0] = jnp.concatenate(dvs, axis=1).astype(dv_ref.dtype)


def _col_spec(s):
    """[B, S, E] block: full batch-element rows, one 128-lane column
    group — lane-aligned strided DMA on the native layout."""
    return pl.BlockSpec((1, s, 128), lambda b, g: (b, 0, g),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _folded_core(q, k, v, head_dim, scale, causal):
    return _folded_fwd(q, k, v, head_dim, scale, causal)


def _folded_fwd(q, k, v, head_dim, scale, causal):
    b, s, e = q.shape
    grp = _heads_per_group(head_dim)
    h = e // head_dim
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          d=head_dim, grp=grp),
        grid=(b, e // 128),
        in_specs=[_col_spec(s)] * 3,
        out_specs=_col_spec(s),
        out_shape=jax.ShapeDtypeStruct((b, s, e), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * s * s * head_dim,
            bytes_accessed=4 * q.size * q.dtype.itemsize,
            transcendentals=b * h * s * s),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(q, k, v)


def _folded_vjp_fwd(q, k, v, head_dim, scale, causal):
    # selective-remat hook (remat_save_attention): this kernel's ONLY
    # backward residuals are q/k/v themselves (the softmax recompute is
    # in-kernel by design — there is no out/lse to buy back), so the
    # named-save policy tags them: under jax.checkpoint the projections
    # feeding attention are then saved instead of recomputed, the
    # closest analog of the BHSD path's saved out+lse.
    from ...core.offload import ATTN_OUT_NAME, name_activation
    q = name_activation(q, ATTN_OUT_NAME)
    k = name_activation(k, ATTN_OUT_NAME)
    v = name_activation(v, ATTN_OUT_NAME)
    return _folded_fwd(q, k, v, head_dim, scale, causal), (q, k, v)


def _folded_vjp_bwd(head_dim, scale, causal, res, g):
    q, k, v = res
    b, s, e = q.shape
    grp = _heads_per_group(head_dim)
    h = e // head_dim
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          d=head_dim, grp=grp),
        grid=(b, e // 128),
        in_specs=[_col_spec(s)] * 4,
        out_specs=[_col_spec(s)] * 3,
        out_shape=[jax.ShapeDtypeStruct((b, s, e), q.dtype)] * 3,
        cost_estimate=pl.CostEstimate(
            # s, dp, dq, dk, dv matmuls over every (q, k) pair
            flops=10 * b * h * s * s * head_dim,
            bytes_accessed=7 * q.size * q.dtype.itemsize,
            transcendentals=b * h * s * s),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(q, k, v, g)
    return dq, dk, dv


_folded_core.defvjp(_folded_vjp_fwd, _folded_vjp_bwd)


def folded_attention_supported(q_shape, k_shape, causal: bool = False,
                               backend: Optional[str] = None) -> bool:
    """Gate for the [B, S, H, D]-layout entry: same-length single-block
    self-attention with head groups that tile 128 lanes exactly.

    Causal caps: the single block pays the full S^2 while the
    streaming kernel skips fully-masked blocks, but at d=64 the
    streaming kernel's half-lane matmuls are inefficient enough that
    folded wins anyway — measured v5e causal fwd+bwd scanned:
    S=512 b64 h12 folded 5.68 vs streaming 6.62 ms/iter, S=1024 b8
    h12 folded 4.33 vs 5.25 — so d=64 causal runs folded through the
    whole single-block range. d=128 causal caps at one 256-block
    (r6, tools/folded_crossover_sweep.py -> FOLDED_CROSSOVER.json,
    replacing r5's unmeasured-conservative 512): calibrating the
    streaming kernel's non-MXU cost from those d=64 measurements and
    halving only its MAC term for full-lane d=128 puts folded at
    ~1.6x streaming's time at S=512 and ~1.5x at S=1024 — the 2x
    causal-pair skip dominates once streaming's contractions are
    full-lane — while S=256 stays folded because streaming is below
    its own measured XLA crossover there (_FLASH_MIN_SEQ). The sweep
    tool re-derives the cap from on-chip data when a chip is
    reachable; FOLDED_CROSSOVER.json records on_chip_pending until
    then."""
    from .flash_attention import _FORCE_DEPTH
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon") and _FORCE_DEPTH == 0:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    if causal and sq > (MAX_SINGLE_BLOCK if d == 64 else 256):
        return False
    return (sq == sk and sq <= MAX_SINGLE_BLOCK and sq % 128 == 0 and
            d in (64, 128) and (h * d) % 128 == 0)


def folded_attention(q, k, v, causal: bool = False,
                     scale: Optional[float] = None):
    """Public entry, layout [B, S, H, D] (matching
    scaled_dot_product_attention); the [B, S, E] fold is a free
    reshape of the projection output — no transpose is materialized
    anywhere on the path."""
    b, s, h, d = q.shape
    e = h * d
    scale = float(scale if scale is not None else 1.0 / math.sqrt(d))
    out = _folded_core(q.reshape(b, s, e), k.reshape(b, s, e),
                       v.reshape(b, s, e), d, scale, bool(causal))
    return out.reshape(b, s, h, d)
