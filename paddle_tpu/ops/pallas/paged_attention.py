"""Ragged paged-attention decode kernel over a block-paged KV pool.

The decode-shaped attention kernel the r5 verdict asked for (weak #1 /
top_next): `PROFILE_DECODE.json` pins b128 GPT-1.3B decode at 13.1
ms/step against an 8.0 ms weights+KV streaming floor, with the KV
prefix (the "loop fusion" category: 5.5 GB/step at 641 GB/s) dominating
— the dense `StaticKVCache` pays full-prefix bandwidth for EVERY
sequence in the batch regardless of its real length. Paper basis:
*Ragged Paged Attention: A High-Performance and Flexible LLM Inference
Kernel for TPU* (PAPERS.md) — KV lives in fixed-size pages indexed by a
per-sequence page table, and the kernel walks only the pages a
sequence actually owns, so a ragged mixed-length batch streams
sum(len_i) tokens of KV instead of B * max(len_i).

Design (house style: lane-native layout, online softmax, ragged skip):

- KV pool: ``[num_pages, page_size, H, D]`` — one page is a contiguous
  ``[page_size, H*D]`` row block, so the per-page DMA is a single
  lane-aligned strided copy (E = H*D is a multiple of 128); heads are
  separated in-kernel exactly like `folded_attention.py`'s column
  groups, never via a materialized transpose.
- Page table: ``[B, max_pages]`` int32 + ``seq_lens [B]`` int32, fed
  through `PrefetchScalarGridSpec` scalar prefetch so the kernel can
  compute page addresses before the grid body runs.
- Grid ``(B,)``; per sequence the kernel walks ``ceil(len/page)``
  pages with a double-buffered async copy HBM->VMEM and an online
  softmax (m, l, acc) carry — pages past the ragged length are never
  fetched, which is the entire bandwidth win.
- int8 KV: pages may be int8 with a per-(page, position, head) abs-max
  scale (layout ``[num_pages, page_size, H]``, quantization/quant.py
  convention ``deq = q * s / 127``); the dequant runs on the VMEM copy
  so HBM traffic is halved.

A pure-JAX reference (`paged_attention_reference`) implements identical
semantics by gathering pages densely — the CPU fast lane and the
numeric tests run it, and the public entry `paged_attention` routes to
it wherever the Mosaic kernel can't run, so both lanes share one
contract (the "CanBeUsed" runtime-selection pattern of
`folded_attention.folded_attention_supported`).
"""

from __future__ import annotations

import contextlib
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Decode pages are streamed once and never revisited, so the page size
# only has to amortize DMA issue overhead; 64 rows x E lanes keeps the
# double-buffered live set (2 pages x K+V) under ~1 MB of VMEM at
# E=2048 bf16 while giving the allocator fine-grained recycling.
DEFAULT_PAGE_SIZE = 64


def _dequant(x, scale):
    """quant.py convention: deq = q * scale / 127 (per page-row/head)."""
    x = x.astype(jnp.float32)
    if scale is None:
        return x
    return x * (scale.astype(jnp.float32) / 127.0)[..., None]


# --------------------------------------------------------------------------
# Pallas kernel (TPU): ragged page walk, double-buffered DMA
# --------------------------------------------------------------------------

def _walk_pages(pt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref,
                k_buf, v_buf, ks_buf, vs_buf, sems, *,
                page: int, scale: float, quantized: bool):
    """Shared ragged page walk: one grid step = one sequence; walks its
    pages with double-buffered DMA and an online softmax, returning the
    NORMALIZED per-head context [H, D] fp32 (the `_decode_kernel` body,
    factored out so the fused-epilogue kernel reuses the exact same
    arithmetic — the bit-identical-per-head property both lean on).

    Scratch: ``k_buf``/``v_buf`` [2, page, H, D] double buffers (+int8
    scale buffers [2, page, H] when quantized); ``sems`` [4, 2] DMA
    semaphores (k, v, k_scale, v_scale) x (slot0, slot1)."""
    b = pl.program_id(0)
    seq_len = len_ref[b]
    n_pages = pl.cdiv(seq_len, page)
    q = q_ref[0].astype(jnp.float32)  # [H, D]
    h, d = q.shape

    def copies(i, slot):
        idx = pt_ref[b, i]
        ops = [pltpu.make_async_copy(kp_ref.at[idx], k_buf.at[slot],
                                     sems.at[0, slot]),
               pltpu.make_async_copy(vp_ref.at[idx], v_buf.at[slot],
                                     sems.at[1, slot])]
        if quantized:
            ops.append(pltpu.make_async_copy(
                ks_ref.at[idx], ks_buf.at[slot], sems.at[2, slot]))
            ops.append(pltpu.make_async_copy(
                vs_ref.at[idx], vs_buf.at[slot], sems.at[3, slot]))
        return ops

    @pl.when(n_pages > 0)
    def _():
        for c in copies(0, 0):
            c.start()

    def body(i, carry):
        m, l, acc = carry  # noqa: E741
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            for c in copies(i + 1, jax.lax.rem(i + 1, 2)):
                c.start()

        for c in copies(i, slot):
            c.wait()
        if quantized:
            k = _dequant(k_buf[slot], ks_buf[slot])
            v = _dequant(v_buf[slot], vs_buf[slot])
        else:
            k = k_buf[slot].astype(jnp.float32)  # [page, H, D]
            v = v_buf[slot].astype(jnp.float32)
        # scores[h, p] = q[h, :] . k[p, h, :]  (heads = batch dims; the
        # head split is index arithmetic, not a transpose)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, page]
        kpos = i * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        s = jnp.where(kpos < seq_len, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)  # noqa: E741
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((h, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1), jnp.float32)
    a0 = jnp.zeros((h, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, a0))
    # empty sequences (len 0) produce defined zeros, not NaN — the
    # continuous-batching engine parks inactive slots at len 0
    return acc / jnp.maximum(l, 1e-30)


def _decode_kernel(pt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref,
                   o_ref, k_buf, v_buf, ks_buf, vs_buf, sems, *,
                   page: int, scale: float, quantized: bool):
    """Raw per-head context output (the pre-r13 kernel contract)."""
    ctx = _walk_pages(pt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref,
                      vs_ref, k_buf, v_buf, ks_buf, vs_buf, sems,
                      page=page, scale=scale, quantized=quantized)
    o_ref[0] = ctx.astype(o_ref.dtype)


def _decode_fused_kernel(pt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref,
                         vs_ref, w_ref, b_ref, o_ref, k_buf, v_buf,
                         ks_buf, vs_buf, sems, *, page: int, scale: float,
                         quantized: bool, has_bias: bool):
    """Fused attention epilogue (r13): the softmax-normalized per-head
    context never leaves VMEM — it is flattened head-major (the same
    [H*D] order the model's reshape produces) and pushed straight
    through the output projection (``w_ref`` [E, E_out] resident in
    VMEM across the whole grid, ``b_ref`` [1, E_out]), so the kernel
    emits the attention BLOCK's output row instead of raw per-head
    context. One launch where the unfused path runs attention + reshape
    + matmul + bias-add (the Tensix/Neptune epilogue-fusion recipe:
    fold the chain into the kernel that already holds the data)."""
    ctx = _walk_pages(pt_ref, len_ref, q_ref, kp_ref, vp_ref, ks_ref,
                      vs_ref, k_buf, v_buf, ks_buf, vs_buf, sems,
                      page=page, scale=scale, quantized=quantized)
    h, d = ctx.shape
    # mimic the unfused lowering's rounding: the standalone kernel
    # rounds the context to the output dtype (bf16 in bf16 serving)
    # BEFORE the model's out-projection matmul, whose MXU dot then
    # accumulates in f32 — round here the same way so fused-vs-unfused
    # on-chip divergence is limited to XLA tiling, not operand
    # precision. (Exact on-chip bit-identity is NOT claimed — see
    # `paged_attention_fused`; the CPU-lane references are bit-equal.)
    row = ctx.astype(o_ref.dtype).reshape(1, h * d)
    out = jax.lax.dot_general(
        row, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [1, E_out]
    if has_bias:
        out = out + b_ref[...].astype(jnp.float32)
    o_ref[0] = out[0].astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, seq_lens,
                         k_scale, v_scale, scale):
    b, h, d = q.shape
    n_pool, page = k_pages.shape[:2]
    quantized = k_scale is not None
    dummy = jnp.zeros((1, 1, 1), jnp.float32)
    ks = k_scale if quantized else dummy
    vs = v_scale if quantized else dummy
    sdt = ks.dtype
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),     # q
            pl.BlockSpec(memory_space=pltpu.ANY),      # k pages (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),      # v pages (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),      # k scales
            pl.BlockSpec(memory_space=pltpu.ANY),      # v scales
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page, h, d), k_pages.dtype),
            pltpu.VMEM((2, page, h, d), v_pages.dtype),
            pltpu.VMEM((2, page, h), sdt),
            pltpu.VMEM((2, page, h), sdt),
            pltpu.SemaphoreType.DMA((4, 2)),
        ],
    )
    kv_bytes = k_pages.dtype.itemsize
    return pl.pallas_call(
        functools.partial(_decode_kernel, page=page, scale=scale,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        cost_estimate=pl.CostEstimate(
            # ragged: the average sequence reads its own prefix once
            flops=4 * int(b) * h * page * d * page_table.shape[1],
            bytes_accessed=2 * n_pool * page * h * d * kv_bytes,
            transcendentals=b * h * page * page_table.shape[1]),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
        if hasattr(pltpu, "CompilerParams") else
        pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",)),
    )(page_table, seq_lens, q, k_pages, v_pages, ks, vs)


def _paged_decode_fused_pallas(q, k_pages, v_pages, page_table, seq_lens,
                               k_scale, v_scale, scale, w, bias):
    """Fused-epilogue variant of :func:`_paged_decode_pallas`: same
    grid/scratch layout plus the projection weight as a VMEM-resident
    block (constant index map — one HBM read for the whole grid) and an
    output row of E_out lanes per sequence."""
    b, h, d = q.shape
    n_pool, page = k_pages.shape[:2]
    e_out = w.shape[1]
    quantized = k_scale is not None
    has_bias = bias is not None
    dummy = jnp.zeros((1, 1, 1), jnp.float32)
    ks = k_scale if quantized else dummy
    vs = v_scale if quantized else dummy
    sdt = ks.dtype
    brow = (bias.reshape(1, e_out) if has_bias
            else jnp.zeros((1, e_out), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),     # q
            pl.BlockSpec(memory_space=pltpu.ANY),      # k pages (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),      # v pages (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),      # k scales
            pl.BlockSpec(memory_space=pltpu.ANY),      # v scales
            pl.BlockSpec((h * d, e_out), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),     # o-proj weight
            pl.BlockSpec((1, e_out), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),     # o-proj bias
        ],
        out_specs=pl.BlockSpec((1, e_out), lambda i, *_: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page, h, d), k_pages.dtype),
            pltpu.VMEM((2, page, h, d), v_pages.dtype),
            pltpu.VMEM((2, page, h), sdt),
            pltpu.VMEM((2, page, h), sdt),
            pltpu.SemaphoreType.DMA((4, 2)),
        ],
    )
    kv_bytes = k_pages.dtype.itemsize
    return pl.pallas_call(
        functools.partial(_decode_fused_kernel, page=page, scale=scale,
                          quantized=quantized, has_bias=has_bias),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, e_out), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * int(b) * h * page * d * page_table.shape[1]
            + 2 * int(b) * h * d * e_out,
            bytes_accessed=(2 * n_pool * page * h * d * kv_bytes
                            + h * d * e_out * w.dtype.itemsize),
            transcendentals=b * h * page * page_table.shape[1]),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
        if hasattr(pltpu, "CompilerParams") else
        pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",)),
    )(page_table, seq_lens, q, k_pages, v_pages, ks, vs, w, brow)


# --------------------------------------------------------------------------
# Pure-JAX reference (CPU fast lane / semantics contract)
# --------------------------------------------------------------------------

def paged_attention_reference(q, k_pages, v_pages, page_table, seq_lens,
                              k_scale=None, v_scale=None,
                              scale: Optional[float] = None,
                              q_offsets=None):
    """Dense-gather reference with identical semantics to the kernel.

    ``q``: [B, Sq, H, D] — query tokens are the LAST Sq positions of
    each sequence unless ``q_offsets`` ([B], absolute position of the
    first query token) overrides it (the ragged-prefill case, where a
    right-padded chunk's true length is shorter than Sq). Positions at
    or beyond ``seq_lens`` are masked; fully-masked rows return zeros
    (not NaN), so empty slots in a fixed-slot batch stay inert.

    Exists for semantics, not bandwidth: the gather materializes the
    padded [B, max_pages*page, H, D] KV — the kernel never does."""
    b, sq, h, d = q.shape
    page = k_pages.shape[1]
    mp = page_table.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def gather(pages, scales):
        g = pages[page_table]  # [B, mp, page, H, D]
        if scales is not None:
            from ...quantization.quant import dequantize_kv
            g = dequantize_kv(g, scales[page_table], jnp.float32)
        else:
            g = g.astype(jnp.float32)
        return g.reshape(b, mp * page, h, d)

    k = gather(k_pages, k_scale)
    v = gather(v_pages, v_scale)
    qf = q.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k,
                        preferred_element_type=jnp.float32) * scale
    if q_offsets is None:
        q_offsets = seq_lens - sq
    kpos = jnp.arange(mp * page, dtype=jnp.int32)
    qpos = q_offsets[:, None] + jnp.arange(sq, dtype=jnp.int32)[None]
    mask = kpos[None, None, :] <= qpos[:, :, None]  # [B, Sq, T]
    logits = jnp.where(mask[:, None], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)  # noqa: E741
    out = jnp.einsum("bhqk,bkhd->bqhd", p / jnp.maximum(l, 1e-30), v)
    any_valid = mask.any(-1)  # [B, Sq]
    out = jnp.where(any_valid[..., None, None], out, 0.0)
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Head sharding (tensor-parallel serving over a `model` mesh axis)
# --------------------------------------------------------------------------

# Trace-time routing state for the mesh-sharded decode engine
# (inference/continuous_batching.py `mesh=`): while a (mesh, axis) pair
# is active, the public entry runs head-sharded under shard_map. The
# head dimension is embarrassingly parallel in attention — every head
# attends its own K/V columns — so the per-device body is exactly the
# single-device kernel on 1/N of the heads, with no collectives and
# therefore BIT-IDENTICAL per-head arithmetic (the property the
# mesh-vs-single-device greedy pins lean on). THREAD-LOCAL: jit traces
# run on the calling thread, and one process may trace a mesh engine
# and a single-device engine concurrently (two server threads); a
# process-global switch would reroute the other thread's trace.
import threading as _threading

_HEAD_SHARDING = _threading.local()


def _default_axis() -> str:
    # topology.SERVING_MODEL_AXIS is the single source of truth for
    # the serving mesh's axis name; imported lazily (ops.pallas must
    # not pull the distributed package at module import)
    from ...distributed.topology import SERVING_MODEL_AXIS
    return SERVING_MODEL_AXIS


@contextlib.contextmanager
def head_sharding(mesh, axis: Optional[str] = None):
    """Route `paged_attention` through the head-sharded shard_map
    dispatch for the duration (a trace-time switch: wrap the jit-traced
    call, not the runtime one). ``axis=None`` = the serving model axis
    (topology.SERVING_MODEL_AXIS)."""
    prev = getattr(_HEAD_SHARDING, "value", None)
    _HEAD_SHARDING.value = (mesh, axis or _default_axis())
    try:
        yield
    finally:
        _HEAD_SHARDING.value = prev


def get_head_sharding() -> Optional[tuple]:
    return getattr(_HEAD_SHARDING, "value", None)


def paged_attention_head_sharded(q, k_pages, v_pages, page_table,
                                 seq_lens, mesh,
                                 axis: Optional[str] = None,
                                 k_scale=None, v_scale=None,
                                 scale: Optional[float] = None,
                                 q_offsets=None):
    """Ragged paged attention with heads sharded over ``mesh[axis]``.

    shard_map over the head dim of q and the KV pools (page table,
    seq_lens and q_offsets replicate — they are host scheduler state);
    each device runs the standard kernel-selection path on its own
    H/N-head slice, so on TPU every shard dispatches the Mosaic
    page-walk kernel and on CPU the dense-gather reference. No
    inter-device communication: attention is head-local. Requires
    ``num_heads % mesh.shape[axis] == 0``."""
    from ...compat import shard_map

    if axis is None:
        axis = _default_axis()
    b, sq, h, d = q.shape
    n = mesh.shape[axis]
    if h % n != 0:
        raise ValueError(
            f"num_heads {h} not divisible by mesh axis {axis!r}={n}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    scale = float(scale)
    p4 = jax.sharding.PartitionSpec(None, None, axis)
    p3 = jax.sharding.PartitionSpec(None, None, axis)
    rep = jax.sharding.PartitionSpec()
    args = [q, k_pages, v_pages, page_table, seq_lens]
    specs = [p4, p4, p4, rep, rep]
    has_scale = k_scale is not None
    if has_scale:
        args += [k_scale, v_scale]
        specs += [p3, p3]
    has_qo = q_offsets is not None
    if has_qo:
        args += [q_offsets]
        specs += [rep]

    def local(*a):
        it = iter(a)
        qq, kp, vp, pt, sl = (next(it) for _ in range(5))
        ks = next(it) if has_scale else None
        vs = next(it) if has_scale else None
        qo = next(it) if has_qo else None
        return _paged_attention_local(qq, kp, vp, pt, sl, k_scale=ks,
                                      v_scale=vs, scale=scale,
                                      q_offsets=qo)

    fn = shard_map(local, mesh=mesh, in_specs=tuple(specs),
                   out_specs=p4, check_rep=False)
    return fn(*args)


# --------------------------------------------------------------------------
# Public entry — runtime kernel selection
# --------------------------------------------------------------------------

def paged_attention_supported(q_shape, kp_shape,
                              backend: Optional[str] = None) -> bool:
    """Gate for the Mosaic kernel: single-token decode over lane-tiling
    head groups. Everything else (ragged prefill chunks, odd head
    widths, CPU/GPU) takes the reference path."""
    from .flash_attention import _FORCE_DEPTH
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon") and _FORCE_DEPTH == 0:
        return False
    b, sq, h, d = q_shape
    page = kp_shape[1]
    return (sq == 1 and d in (64, 128) and (h * d) % 128 == 0 and
            page % 8 == 0)


def _paged_attention_local(q, k_pages, v_pages, page_table, seq_lens,
                           k_scale=None, v_scale=None,
                           scale: Optional[float] = None,
                           q_offsets=None):
    """Single-device kernel selection (the pre-mesh public entry): the
    Mosaic page-walk kernel where the shape gate admits, the
    dense-gather reference elsewhere. Also the per-shard body of the
    head-sharded dispatch."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    scale = float(scale)
    if q_offsets is None and paged_attention_supported(
            q.shape, k_pages.shape):
        out = _paged_decode_pallas(
            q.reshape(b, h, d), k_pages, v_pages,
            page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
            k_scale, v_scale, scale)
        return out.reshape(b, sq, h, d)
    return paged_attention_reference(
        q, k_pages, v_pages, page_table, seq_lens,
        k_scale=k_scale, v_scale=v_scale, scale=scale,
        q_offsets=q_offsets)


def paged_attention(q, k_pages, v_pages, page_table, seq_lens,
                    k_scale=None, v_scale=None,
                    scale: Optional[float] = None, q_offsets=None):
    """Ragged paged attention over a block-paged KV pool.

    q: [B, Sq, H, D]; k_pages/v_pages: [P, page, H, D] (float or int8
    with k_scale/v_scale [P, page, H]); page_table: [B, max_pages]
    int32; seq_lens: [B] int32 lengths INCLUDING the already-appended
    query tokens. Returns [B, Sq, H, D].

    Under an active :func:`head_sharding` context (the mesh-sharded
    decode engine wraps its jit traces in one) the call runs
    head-sharded via shard_map; otherwise single-device kernel
    selection."""
    hs = get_head_sharding()
    if hs is not None:
        mesh, axis = hs
        return paged_attention_head_sharded(
            q, k_pages, v_pages, page_table, seq_lens, mesh, axis=axis,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
            q_offsets=q_offsets)
    return _paged_attention_local(
        q, k_pages, v_pages, page_table, seq_lens, k_scale=k_scale,
        v_scale=v_scale, scale=scale, q_offsets=q_offsets)


# --------------------------------------------------------------------------
# Fused attention epilogue (r13): attention + out-projection, one launch
# --------------------------------------------------------------------------

# VMEM budget for the resident o-projection weight block: the fused
# kernel keeps W [E, E_out] live next to the double-buffered page set,
# so the gate admits only weights that fit comfortably (v4/v5 cores
# carry 16 MB VMEM; 8 MB leaves the page buffers + q + output headroom).
_FUSED_W_VMEM_BYTES = 8 * 1024 * 1024


def fused_epilogue_supported(q_shape, kp_shape, w_shape,
                             backend: Optional[str] = None,
                             w_itemsize: int = 4) -> bool:
    """Gate for the Mosaic fused-epilogue kernel: everything
    :func:`paged_attention_supported` requires, plus a lane-tiling
    projection whose weight block fits the VMEM budget
    (``w_itemsize``: the weight's storage bytes/element — the kernel
    keeps W in storage dtype, so a bf16 [2048, 2048] head fits where
    an fp32 one does not)."""
    if not paged_attention_supported(q_shape, kp_shape, backend):
        return False
    e_in, e_out = w_shape
    _, _, h, d = q_shape
    return (e_in == h * d and e_out % 128 == 0 and
            e_in * e_out * int(w_itemsize) <= _FUSED_W_VMEM_BYTES)


def paged_attention_fused_reference(q, k_pages, v_pages, page_table,
                                    seq_lens, w, bias=None,
                                    k_scale=None, v_scale=None,
                                    scale: Optional[float] = None,
                                    q_offsets=None):
    """Dense-gather reference for the fused epilogue: EXACTLY the
    unfused model math — :func:`paged_attention_reference`, the
    head-concat reshape, ``x @ W`` (ops.nn_functional.linear semantics)
    and the bias add — composed inside one op, so the fused engine's
    greedy tokens are bit-identical to the unfused engine on the CPU
    lane (the jaxpr the trace emits is the same one the unfused layers
    emit; only the launch/op count differs)."""
    ctx = paged_attention_reference(
        q, k_pages, v_pages, page_table, seq_lens, k_scale=k_scale,
        v_scale=v_scale, scale=scale, q_offsets=q_offsets)
    b, sq, h, d = ctx.shape
    out = jnp.matmul(ctx.reshape(b, sq, h * d), w)
    if bias is not None:
        out = out + bias
    return out


def paged_attention_fused(q, k_pages, v_pages, page_table, seq_lens,
                          w, bias=None, k_scale=None, v_scale=None,
                          scale: Optional[float] = None, q_offsets=None):
    """Ragged paged attention with the output-projection epilogue fused
    in: returns the attention BLOCK's output ``[B, Sq, E_out]`` instead
    of raw per-head context (``w``: [H*D, E_out] o-projection weight,
    ``bias``: optional [E_out]).

    Kernel selection mirrors :func:`paged_attention`: under an active
    :func:`head_sharding` context the attention runs head-sharded and
    the projection stays in the same traced program (GSPMD partitions
    the contraction over the head-grouped rows exactly as the unfused
    RowParallelLinear would — no separate launch, identical math);
    single-device, the Mosaic fused-epilogue kernel runs where
    :func:`fused_epilogue_supported` admits, the dense-gather fused
    reference elsewhere.

    Bit-identity contract: the REFERENCE composes the exact unfused
    jnp ops, so fused-vs-unfused greedy outputs are bit-equal wherever
    it runs (the CPU CI lane). The Mosaic kernel mimics the unfused
    lowering's rounding (context rounded to the output dtype before
    the epilogue dot, f32 accumulation) but on-chip bit-parity with
    the separately-launched unfused programs is chip-pending
    validation — validate with the fused_decode A/B on a chip-attached
    host before relying on cross-mode determinism there."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    scale = float(scale)
    hs = get_head_sharding()
    if hs is not None:
        mesh, axis = hs
        ctx = paged_attention_head_sharded(
            q, k_pages, v_pages, page_table, seq_lens, mesh, axis=axis,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
            q_offsets=q_offsets)
        out = jnp.matmul(ctx.reshape(b, sq, h * d), w)
        if bias is not None:
            out = out + bias
        return out
    if q_offsets is None and fused_epilogue_supported(
            q.shape, k_pages.shape, w.shape,
            w_itemsize=w.dtype.itemsize):
        out = _paged_decode_fused_pallas(
            q.reshape(b, h, d), k_pages, v_pages,
            page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
            k_scale, v_scale, scale, w, bias)
        return out.reshape(b, sq, w.shape[1])
    # epilogue not in-kernel: compose the STANDARD kernel-selected
    # attention (_paged_attention_local — the Mosaic page-walk kernel
    # on TPU where its gate admits, the dense-gather reference on the
    # CPU lane) with the same epilogue ops, still as one dispatch op.
    # Falling back to the dense reference here would silently hand the
    # big-E decode hot path (e.g. a 1.3B head over the VMEM budget)
    # the worst kernel on exactly the backend the fusion targets.
    ctx = _paged_attention_local(
        q, k_pages, v_pages, page_table, seq_lens, k_scale=k_scale,
        v_scale=v_scale, scale=scale, q_offsets=q_offsets)
    out = jnp.matmul(ctx.reshape(b, sq, h * d), w)
    if bias is not None:
        out = out + bias
    return out
