"""Flash attention Pallas/Mosaic kernel for TPU.

The fused-attention hot op (reference analog: the CUDA fusion
paddle/fluid/operators/fused/multihead_matmul_op.cu — rebuilt here as a
proper online-softmax flash kernel instead of a translated fusion).

Forward: grid (B, H, Sq/BQ, Sk/BK); the K/V blocks stream through the
LAST grid axis while running (max, sumexp, acc) state lives in VMEM
scratch — the output block is revisited across the K axis and written on
its final step. Backward: FlashAttention-2 split — one kernel recomputes
p-blocks to build dK/dV (K blocks outer, Q blocks streaming), another
builds dQ (Q outer, K streaming); both use the saved logsumexp and
delta = rowsum(dO * O).

Only BLOCKS ever sit in VMEM (the r3 fix: the previous design mapped the
full [S, D] counterpart operand per (batch, head) into VMEM and
fori_loop'ed over it, capping S*D at the ~16 MB scoped-vmem budget —
S=8192 x D=128 failed to compile), so sequence length is bounded by HBM,
not VMEM. All matmuls run on the MXU in fp32 accumulation
(preferred_element_type=float32); causal runs skip fully-masked blocks
via pl.when on the block indices.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
# older jax spells CompilerParams TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) or \
    pltpu.TPUCompilerParams

# 512-blocks measured fastest on TPU v5e (grad 4.2 ms vs 8.0 ms at 128
# for B8 H12 S1024 D64); auto-clamped to the sequence length.
# PT_FLASH_BLOCK_Q/K override for shape-specific tuning (the analog of
# the reference's per-kernel-key JIT selection, operators/jit/README).
import contextlib as _contextlib
import os as _os

DEFAULT_BLOCK_Q = int(_os.environ.get("PT_FLASH_BLOCK_Q", 512))
DEFAULT_BLOCK_K = int(_os.environ.get("PT_FLASH_BLOCK_K", 512))
_NEG_INF = -1e30

# batch/head/outer-block grid axes carry no cross-iteration state ->
# Mosaic may pipeline them; the LAST axis streams the counterpart blocks
# through scratch accumulators and must run in order ("arbitrary").
_GRID_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, acc_ref, m_ref, l_run_ref,
                *, scale, causal, block_q, block_k, nk):
    qb = pl.program_id(2)
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_run_ref[...] = jnp.zeros_like(l_run_ref)

    # causal: K blocks fully above the diagonal contribute nothing.
    # (r4, measured: splitting the body into masked-diagonal vs
    # unmasked-fully-visible pl.when branches to skip the iota/where
    # chain on interior blocks made things WORSE — b8 s1024 d128 causal
    # fwd+bwd 5.06 -> 8.39 ms scanned wall-clock, and both sides carry
    # the same ~3 ms amortized dispatch floor so the true device-time
    # regression is steeper; the extra branch breaks Mosaic's pipeline.
    # The single masked body stays.)
    relevant = (kb * block_k <= (qb + 1) * block_q - 1) if causal else True

    @pl.when(relevant)
    def _step():
        # operands stay in the input dtype (bf16 on the MXU at full
        # rate); all accumulation is f32 via preferred_element_type
        q = q_ref[0, 0]  # [BQ, D]
        k_blk = k_ref[0, 0]  # [BK, D]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK] f32
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_run = m_ref[:, :1]  # [BQ, 1]
        l_run = l_run_ref[:, :1]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_run, m_blk)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_run - m_new)
        l_new = l_run * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_run_ref[...] = jnp.broadcast_to(l_new, l_run_ref.shape)

    @pl.when(kb == nk - 1)
    def _finish():
        denom = jnp.maximum(l_run_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)
        # logsumexp per row, stored [BQ, 1] (lane-1 layout keeps the
        # block spec legal on TPU: last dim equals the array dim)
        l_ref[0, 0] = m_ref[:, :1] + jnp.log(denom)


def _fwd_single_block_kernel(q_ref, k_ref, v_ref, o_ref, l_ref,
                             *, scale, causal, block_q, block_k):
    """Forward for the nk == 1 case (the whole K axis is one block,
    e.g. S=512 at the default 512 block): a plain in-register softmax.
    The streaming kernel's online-softmax machinery — running max,
    alpha rescale of the accumulator, (BQ, 128) m/l scratch broadcasts
    — exists to merge MULTIPLE K blocks and is pure overhead with one."""
    qb = pl.program_id(2)
    q = q_ref[0, 0]  # [BQ, D]
    k_blk = k_ref[0, 0]
    v_blk = v_ref[0, 0]
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [BQ, BK] f32
    if causal:
        q_pos = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    denom = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
    acc = jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0, 0] = (acc / denom).astype(o_ref.dtype)
    l_ref[0, 0] = m + jnp.log(denom)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, nk):
    qb = pl.program_id(2)
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    relevant = (kb * block_k <= (qb + 1) * block_q - 1) if causal else True

    @pl.when(relevant)
    def _step():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [BQ, 1]
        delta = delta_ref[0, 0]  # [BQ, 1]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_acc_ref,
                      *, scale, causal, block_q, block_k, nk):
    """Single-pass backward for the nq == 1 case (the whole Q axis is
    one block, e.g. S=512 at the default 512 block): grid (B, H, nk)
    streams K blocks, dQ accumulates in scratch over the LAST grid axis
    (the one revisiting Pallas TPU allows), dK/dV are per-block
    outputs. Computes the score block and its exp ONCE per (q,k) pair
    — the general two-kernel FlashAttention-2 backward recomputes them
    in both passes (7 matmuls + 2 exps vs 5 matmuls + 1 exp here)."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    # causal with sk > sq (nq == 1): K blocks entirely past the last Q
    # row are fully masked — p would underflow to exact zero, so skip
    # the matmuls/DMA-consumption and zero-fill their dk/dv outputs
    # (dq accumulates nothing from them). The K/V input specs clamp the
    # block index for these steps so the HBM fetch is skipped too.
    relevant = (kb * block_k <= block_q - 1) if causal else True

    @pl.when(relevant)
    def _step():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [BQ, 1]
        delta = delta_ref[0, 0]  # [BQ, 1]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv_ref[0, 0] = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[0, 0] = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(jnp.logical_not(relevant))
        def _masked_block():
            dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
            dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, scale, causal, block_q, block_k, nq):
    kb = pl.program_id(2)
    qb = pl.program_id(3)

    @pl.when(qb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: Q blocks fully above the diagonal see none of this K block
    relevant = ((qb + 1) * block_q - 1 >= kb * block_k) if causal else True

    @pl.when(relevant)
    def _step():
        k_blk = k_ref[0, 0]  # [BK, D]
        v_blk = v_ref[0, 0]
        q = q_ref[0, 0]  # [BQ, D]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [BQ, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _spec_outer(block, d):
    """Block indexed by the OUTER block axis (grid dim 2), constant over
    the streaming axis (grid dim 3).

    Note (r4, measured): a "packed" variant of these specs that kept
    heads as d-wide column blocks over the natural [B, S, H*D] layout —
    eliminating the [B,S,H,D]->[B,H,S,D] transpose round-trip — was
    tried and REMOVED: Mosaic cannot lower d=64 column blocks (the last
    block dim must divide 128 or span the array), and at d=128 the
    strided block DMA cost more than the transposes it saved (GPT-1.3B
    step 254.0 vs 251.7 ms)."""
    return pl.BlockSpec((1, 1, block, d), lambda b, h, i, j: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _spec_inner(block, d, clamp=None):
    """Block streamed by the INNER grid axis (grid dim 3). ``clamp(i, j)``
    maps the stream index per outer block — causal kernels clamp masked
    steps to the last/first relevant block, so Pallas sees a repeated
    block index and skips the HBM re-fetch for steps pl.when guards off.
    """
    if clamp is None:
        return pl.BlockSpec((1, 1, block, d),
                            lambda b, h, i, j: (b, h, j, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, 1, block, d),
                        lambda b, h, i, j: (b, h, clamp(i, j), 0),
                        memory_space=pltpu.VMEM)


def _spec_lane1_outer(block):
    return pl.BlockSpec((1, 1, block, 1),
                        lambda b, h, i, j: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _spec_lane1_inner(block, clamp=None):
    if clamp is None:
        return pl.BlockSpec((1, 1, block, 1),
                            lambda b, h, i, j: (b, h, j, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, 1, block, 1),
                        lambda b, h, i, j: (b, h, clamp(i, j), 0),
                        memory_space=pltpu.VMEM)


def _spec3_indexed(block, d, lim=None):
    """3-dim-grid spec: block selected by the grid's third axis.
    ``lim`` clamps the index (causal fused-bwd: K blocks past the last
    Q row repeat the last relevant block so Pallas skips the fetch)."""
    if lim is None:
        return pl.BlockSpec((1, 1, block, d),
                            lambda b, h, i: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, 1, block, d),
                        lambda b, h, i: (b, h, jnp.minimum(i, lim), 0),
                        memory_space=pltpu.VMEM)


def _spec3_pinned(block, d):
    """3-dim-grid spec: the same (b, h) block regardless of the third
    grid axis (the single outer block of an nq==1/nk==1 kernel)."""
    return pl.BlockSpec((1, 1, block, d),
                        lambda b, h, i: (b, h, 0, 0),
                        memory_space=pltpu.VMEM)


def _kv_clamp(causal, block_q, block_k):
    """For Q-outer kernels: the last K block visible to Q block i."""
    if not causal:
        return None
    return lambda i, j: jnp.minimum(
        j, ((i + 1) * block_q - 1) // block_k)


def _q_clamp(causal, block_q, block_k):
    """For K-outer kernels: the first Q block that sees K block i."""
    if not causal:
        return None
    return lambda i, j: jnp.maximum(j, (i * block_k) // block_q)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nk = sk // block_k
    if nk == 1:
        # one K block: plain softmax kernel, no streaming axis — every
        # grid dim is parallel and the online-softmax scratch vanishes
        out, lse = pl.pallas_call(
            functools.partial(_fwd_single_block_kernel, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(b, h, sq // block_q),
            in_specs=[_spec3_indexed(block_q, d),
                      _spec3_pinned(block_k, d),
                      _spec3_pinned(block_k, d)],
            out_specs=[_spec3_indexed(block_q, d),
                       _spec3_indexed(block_q, 1)],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
            ],
            cost_estimate=pl.CostEstimate(
                flops=4 * b * h * sq * sk * d,
                bytes_accessed=(q.size + k.size + v.size) *
                q.dtype.itemsize,
                transcendentals=b * h * sq * sk),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "parallel")),
        )(q, k, v)
        return out, lse
    grid = (b, h, sq // block_q, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk)
    kvc = _kv_clamp(causal, block_q, block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_spec_outer(block_q, d), _spec_inner(block_k, d, kvc),
                  _spec_inner(block_k, d, kvc)],
        out_specs=[
            _spec_outer(block_q, d),
            _spec_lane1_outer(block_q),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * sq * sk * d,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * h * sq * sk),
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v)
    return out, lse


def _flash_bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k,
               g_lse=None, delta=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)  # [B,H,Sq,1]
    if g_lse is not None:
        # lse cotangent folds into delta: d lse/d s_j = p_j, so the lse
        # contribution to ds is p * g_lse — i.e. ds = p*(dp - (delta -
        # g_lse)). No kernel change needed.
        delta = delta - g_lse.astype(jnp.float32)

    if nq == 1:
        # the whole Q axis is one block: a single fused pass computes
        # dQ/dK/dV together (one score recompute instead of two).
        # Measured v5e: neutral on the isolated scanned microbench but
        # -14.5 ms (-6.7%) on the full BERT-base body step, where the
        # halved launch count composes with XLA's surrounding schedule.
        kv_lim = ((block_q - 1) // block_k) if causal else None
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, nk=nk),
            grid=(b, h, nk),
            in_specs=[_spec3_pinned(block_q, d),
                      _spec3_indexed(block_k, d, kv_lim),
                      _spec3_indexed(block_k, d, kv_lim),
                      _spec3_pinned(block_q, d),
                      _spec3_pinned(block_q, 1),
                      _spec3_pinned(block_q, 1)],
            out_specs=[_spec3_pinned(block_q, d),
                       _spec3_indexed(block_k, d),
                       _spec3_indexed(block_k, d)],
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                # 5 matmuls over every (q, k) pair: dv, dp, dk, dq, s
                flops=10 * b * h * sq * sk * d,
                bytes_accessed=(2 * q.size + 2 * do.size + 2 * k.size +
                                2 * v.size) * q.dtype.itemsize,
                transcendentals=b * h * sq * sk),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    # dQ: Q blocks outer (parallel), K/V blocks stream on the last axis
    kvc = _kv_clamp(causal, block_q, block_k)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(b, h, nq, nk),
        in_specs=[
            _spec_outer(block_q, d),
            _spec_inner(block_k, d, kvc),
            _spec_inner(block_k, d, kvc),
            _spec_outer(block_q, d),
            _spec_lane1_outer(block_q), _spec_lane1_outer(block_q),
        ],
        out_specs=_spec_outer(block_q, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v, do, lse, delta)

    # dK/dV: K blocks outer (parallel), Q/dO/lse/delta stream
    qc = _q_clamp(causal, block_q, block_k)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(b, h, nk, nq),
        in_specs=[
            _spec_inner(block_q, d, qc),
            _spec_outer(block_k, d),
            _spec_outer(block_k, d),
            _spec_inner(block_q, d, qc),
            _spec_lane1_inner(block_q, qc), _spec_lane1_inner(block_q, qc),
        ],
        out_specs=[_spec_outer(block_k, d),
                   _spec_outer(block_k, d)],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd_lse(q, k, v, scale, causal, block_q, block_k):
    """(out, lse) with lse DIFFERENTIABLE — the building block for
    blockwise/ring merging, where gradients flow through the logsumexp
    merge weights as well as the block outputs."""
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)


def _flash_lse_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    # selective-remat hook: when ATTN_OUT_NAME is an active saved name
    # (core.offload.set_remat_saved_names, e.g. via
    # GPTConfig.remat_save_attention), tag BOTH backward residuals this
    # kernel produces — out alone is not enough, the FlashAttention-2
    # backward also consumes lse, and an unsaved lse forces the whole
    # flash forward to recompute under jax.checkpoint
    from ...core.offload import ATTN_OUT_NAME, name_activation
    out = name_activation(out, ATTN_OUT_NAME)
    lse = name_activation(lse, ATTN_OUT_NAME)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g_out, scale, causal,
                            block_q, block_k, g_lse=g_lse)
    return dq, dk, dv


_flash_attention_bhsd_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_lse(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        block_q: int = DEFAULT_BLOCK_Q,
                        block_k: int = DEFAULT_BLOCK_K):
    """flash_attention that also returns the per-row logsumexp
    ([B, S, H] f32), both differentiable. Layout [B, S, H, D]."""
    b, sq, h, d = q.shape
    block_q, block_k = _resolve_blocks(sq, k.shape[1], block_q, block_k)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qT = jnp.swapaxes(q, 1, 2)
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    out, lse = _flash_attention_bhsd_lse(qT, kT, vT, float(scale),
                                         bool(causal), block_q, block_k)
    return jnp.swapaxes(out, 1, 2), jnp.swapaxes(lse[..., 0], 1, 2)


def _resolve_blocks(sq, sk, block_q, block_k):
    """Largest 128-multiple block that divides the sequence length, capped
    at the requested block — so S=640 runs with 128-blocks rather than
    falling off the flash path entirely."""
    def best(s, cap):
        pick = 0
        m = 128
        while m <= min(cap, s):
            if s % m == 0:
                pick = m
            m += 128
        return pick or cap
    return best(sq, block_q), best(sk, block_k)


_FORCE_DEPTH = 0


@_contextlib.contextmanager
def force_flash_for_aot():
    """Treat the flash kernel as supported while compiling FOR a TPU
    topology ON a CPU host (jax.default_backend() reports the host, not
    the compile target). Scoped — unlike a leftover env var, it cannot
    leak into a real CPU/GPU execution and fail at Mosaic lowering.
    Used by tools/scale_proof.py around its AOT lower+compile."""
    global _FORCE_DEPTH
    _FORCE_DEPTH += 1
    try:
        yield
    finally:
        _FORCE_DEPTH -= 1


def flash_attention_supported(q_shape, k_shape, backend: Optional[str] =
                              None, block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K) -> bool:
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon") and _FORCE_DEPTH == 0:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    block_q, block_k = _resolve_blocks(sq, sk, block_q, block_k)
    return (sq % block_q == 0 and sk % block_k == 0 and
            block_q % 128 == 0 and block_k % 128 == 0 and
            d in (64, 128, 256))


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Public entry, layout [B, S, H, D] (matching
    scaled_dot_product_attention). One vjp stack for both entries:
    this is flash_attention_lse with the lse dropped (its unused
    cotangent arrives as zeros, so delta is unchanged)."""
    out, _ = flash_attention_lse(q, k, v, causal=causal, scale=scale,
                                 block_q=block_q, block_k=block_k)
    return out
