"""Flash attention Pallas/Mosaic kernel for TPU.

The fused-attention hot op (reference analog: the CUDA fusion
paddle/fluid/operators/fused/multihead_matmul_op.cu — rebuilt here as a
proper online-softmax flash kernel instead of a translated fusion).

Forward: grid (B, H, Sq/BQ); K/V stream through VMEM in BK-blocks with the
running (max, sumexp, acc) update; logsumexp is saved for backward.
Backward: FlashAttention-2 split — one kernel recomputes p-blocks to build
dK/dV (grid over K blocks), another builds dQ (grid over Q blocks); both
use the saved logsumexp and delta = rowsum(dO * O).

All matmuls run on the MXU in fp32 accumulation
(preferred_element_type=float32); causal runs skip fully-masked K blocks
via a dynamic fori_loop bound.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512-blocks measured fastest on TPU v5e (grad 4.2 ms vs 8.0 ms at 128
# for B8 H12 S1024 D64); auto-clamped to the sequence length.
# PT_FLASH_BLOCK_Q/K override for shape-specific tuning (the analog of
# the reference's per-kernel-key JIT selection, operators/jit/README).
import os as _os

DEFAULT_BLOCK_Q = int(_os.environ.get("PT_FLASH_BLOCK_Q", 512))
DEFAULT_BLOCK_K = int(_os.environ.get("PT_FLASH_BLOCK_K", 512))
_NEG_INF = -1e30

# batch/head grid axes have no cross-iteration state -> Mosaic may run
# them in any order / pipelined; the block axis carries nothing either
# (each q- or k-block writes its own output slice) but keeps "arbitrary"
# so revisiting-order guarantees hold for the full-array K/V blocks.
_GRID_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, scale, causal,
                block_q, block_k, sk):
    qb = pl.program_id(2)
    # operands stay in the input dtype (bf16 on the MXU at full rate);
    # all accumulation is f32 via preferred_element_type
    q = q_ref[0, 0]  # [BQ, D]
    nk = sk // block_k
    if causal:
        # highest K block any row of this Q block can see
        nk_dyn = jnp.minimum(((qb + 1) * block_q + block_k - 1) // block_k,
                             nk)
    else:
        nk_dyn = nk

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        acc, m_run, l_run = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK] f32
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_run, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_run - m_new)
        l_new = l_run * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m_run, l_run = jax.lax.fori_loop(0, nk_dyn, body, (acc0, m0, l0))
    denom = jnp.maximum(l_run, 1e-30)
    o_ref[0, 0] = (acc / denom[:, None]).astype(o_ref.dtype)
    # logsumexp per row, stored [BQ, 1] (lane-1 layout keeps the block
    # spec legal on TPU: last dim equals the array dim)
    l_ref[0, 0] = (m_run + jnp.log(denom))[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, sk):
    qb = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]  # [BQ, 1]
    delta = delta_ref[0, 0]  # [BQ, 1]
    nk = sk // block_k
    nk_dyn = jnp.minimum(((qb + 1) * block_q + block_k - 1) // block_k, nk)\
        if causal else nk
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, dq):
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk_dyn,
                           body, jnp.zeros_like(q, jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k, sq):
    kb = pl.program_id(2)
    k_blk = k_ref[0, 0]  # [BK, D]
    v_blk = v_ref[0, 0]
    nq = sq // block_q
    start_qb = (kb * block_k) // block_q if causal else 0
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q), :]  # [BQ, 1]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros_like(k_blk, jnp.float32)
    dv0 = jnp.zeros_like(v_blk, jnp.float32)
    start = start_qb if causal else 0
    dk, dv = jax.lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _spec_q(block_q, d):
    return pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _spec_full(s, d):
    return pl.BlockSpec((1, 1, s, d), lambda b, h, i: (b, h, 0, 0),
                        memory_space=pltpu.VMEM)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    grid = (b, h, sq // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, sk=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_spec_q(block_q, d), _spec_full(sk, d), _spec_full(sk, d)],
        out_specs=[
            _spec_q(block_q, d),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * sq * sk * d,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * h * sq * sk),
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v)
    return out, lse


def _flash_bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,Sq,1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, sk=sk),
        grid=(b, h, sq // block_q),
        in_specs=[
            _spec_q(block_q, d), _spec_full(sk, d), _spec_full(sk, d),
            _spec_q(block_q, d),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=_spec_q(block_q, d),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, sq=sq),
        grid=(b, h, sk // block_k),
        in_specs=[
            _spec_full(sq, d),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
            _spec_full(sq, d),
            pl.BlockSpec((1, 1, sq, 1), lambda b_, h_, i: (b_, h_, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, sq, 1), lambda b_, h_, i: (b_, h_, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i: (b_, h_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        compiler_params=_GRID_SEMANTICS,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q,
                            block_k)
    return dq, dk, dv


_flash_attention_bhsd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _resolve_blocks(sq, sk, block_q, block_k):
    """Largest 128-multiple block that divides the sequence length, capped
    at the requested block — so S=640 runs with 128-blocks rather than
    falling off the flash path entirely."""
    def best(s, cap):
        pick = 0
        m = 128
        while m <= min(cap, s):
            if s % m == 0:
                pick = m
            m += 128
        return pick or cap
    return best(sq, block_q), best(sk, block_k)


def flash_attention_supported(q_shape, k_shape, backend: Optional[str] =
                              None, block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K) -> bool:
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    block_q, block_k = _resolve_blocks(sq, sk, block_q, block_k)
    return (sq % block_q == 0 and sk % block_k == 0 and
            block_q % 128 == 0 and block_k % 128 == 0 and
            d in (64, 128, 256))


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Public entry, layout [B, S, H, D] (matching
    scaled_dot_product_attention)."""
    b, sq, h, d = q.shape
    block_q, block_k = _resolve_blocks(sq, k.shape[1], block_q, block_k)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qT = jnp.swapaxes(q, 1, 2)
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    out = _flash_attention_bhsd(qT, kT, vT, float(scale), bool(causal),
                                block_q, block_k)
    return jnp.swapaxes(out, 1, 2)
