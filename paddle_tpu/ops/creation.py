"""Tensor creation ops (pure functional, jax-native).

Reference parity: python/paddle/tensor/creation.py (to_tensor, zeros, ones,
full, arange, linspace, eye, tril/triu, meshgrid, diag, assign).
These are raw jax functions — the eager Tensor-wrapping layer lives in
paddle_tpu.dispatch.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, default_dtype


def _dt(dtype, like=None):
    if dtype is not None:
        return convert_dtype(dtype)
    if like is not None:
        return None  # let jnp infer
    return default_dtype()


def to_array(data, dtype=None):
    if dtype is not None:
        return jnp.asarray(data, dtype=convert_dtype(dtype))
    arr = jnp.asarray(data)
    # Python floats default to the framework default dtype, matching the
    # reference's to_tensor behavior (float64 literals land as float32).
    if isinstance(data, (float, list, tuple, np.ndarray)) and \
            jnp.issubdtype(arr.dtype, jnp.floating) and \
            arr.dtype == jnp.float64:
        arr = arr.astype(default_dtype())
    return arr


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype=_dt(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype=_dt(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype=_dt(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt(dtype, like=x))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dt(dtype, like=x))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dt(dtype, like=x))


def empty(shape, dtype=None):
    return jnp.empty(shape, dtype=_dt(dtype))


def empty_like(x, dtype=None):
    return jnp.empty_like(x, dtype=_dt(dtype, like=x))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            dtype = default_dtype()
        else:
            dtype = jnp.int32
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=_dt(dtype))


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype))


def diag(x, offset=0, padding_value=0):
    x = jnp.asarray(x)
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, dtype=x.dtype)
        return base + jnp.diag(x - 0, offset) - jnp.diag(
            jnp.full_like(x, padding_value), offset)
    return jnp.diag(x, offset)


def diagflat(x, offset=0):
    return jnp.diagflat(x, offset)


def tril(x, diagonal=0):
    return jnp.tril(x, diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, diagonal)


def meshgrid(*arrays, indexing="ij"):
    arrays = arrays[0] if len(arrays) == 1 and isinstance(
        arrays[0], (list, tuple)) else arrays
    return list(jnp.meshgrid(*arrays, indexing=indexing))


def assign(x, output=None):
    return jnp.asarray(x)


def clone(x):
    return jnp.array(x, copy=True)


def tril_indices(row, col=None, offset=0):
    col = row if col is None else col
    r, c = jnp.tril_indices(row, offset, col)
    return jnp.stack([r, c])


def triu_indices(row, col=None, offset=0):
    col = row if col is None else col
    r, c = jnp.triu_indices(row, offset, col)
    return jnp.stack([r, c])


def complex_(real, imag):
    return jnp.asarray(real) + 1j * jnp.asarray(imag)
