"""Metric / evaluation op family.

Reference parity: paddle/fluid/operators/ edit_distance_op, ctc_align_op,
mean_iou_op, precision_recall_op, chunk_eval_op, detection_map_op,
positive_negative_pair_op. These run as evaluation ops; the sequential/
dynamic ones (chunk_eval, detection_map) are host-side eager ops like the
reference's CPU-only kernels, the dense ones are jittable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def edit_distance(hyps, refs, hyp_lengths=None, ref_lengths=None,
                  normalized: bool = True):
    """Levenshtein distance per sequence pair (edit_distance_op.h).

    hyps/refs: [batch, maxlen] int tokens with per-row valid lengths.
    Returns (distances [batch, 1] float, sequence_num [1]). Jittable:
    the DP runs over the padded grid with length masking.
    """
    hyps = jnp.asarray(hyps)
    refs = jnp.asarray(refs)
    b, m = hyps.shape
    n = refs.shape[1]
    hl = jnp.asarray(hyp_lengths) if hyp_lengths is not None else \
        jnp.full((b,), m)
    rl = jnp.asarray(ref_lengths) if ref_lengths is not None else \
        jnp.full((b,), n)

    # DP rows over hyp positions; carry = dp row [batch, n+1]
    row0 = jnp.broadcast_to(jnp.arange(n + 1, dtype=jnp.float32),
                            (b, n + 1))

    def step(prev, i):
        # prev: dp[i-1, :]; compute dp[i, :]
        cost_del = prev + 1.0                         # delete hyp[i-1]
        sub = (hyps[:, i - 1][:, None] != refs).astype(jnp.float32)
        cost_sub = prev[:, :-1] + sub                 # substitute
        first = jnp.full((b, 1), jnp.float32(i))

        def inner(carry, j):
            # carry: dp[i, j-1]
            val = jnp.minimum(jnp.minimum(
                cost_del[:, j], cost_sub[:, j - 1]), carry + 1.0)
            return val, val

        _, rest = jax.lax.scan(inner, first[:, 0],
                               jnp.arange(1, n + 1))
        row = jnp.concatenate([first, rest.T], axis=1)
        return row, row

    _, stacked = jax.lax.scan(step, row0, jnp.arange(1, m + 1))
    # dp value at (hl, rl) per row: gather from the right dp row
    all_rows = jnp.concatenate([row0[None], stacked],
                               axis=0)  # [m+1, b, n+1]
    dist = all_rows[hl, jnp.arange(b), rl]
    if normalized:
        dist = dist / jnp.maximum(rl.astype(jnp.float32), 1.0)
    return dist[:, None], jnp.asarray([b])


def ctc_align(x, lengths, blank: int = 0, merge_repeated: bool = True):
    """Collapse CTC paths: merge repeats then drop blanks
    (ctc_align_op.h). x [batch, maxlen] ints; returns (aligned
    [batch, maxlen] zero-padded, new_lengths)."""
    x = jnp.asarray(x)
    b, m = x.shape
    valid = jnp.arange(m)[None, :] < jnp.asarray(lengths)[:, None]
    if merge_repeated:
        first = jnp.concatenate(
            [jnp.ones((b, 1), bool), x[:, 1:] != x[:, :-1]], axis=1)
    else:
        first = jnp.ones((b, m), bool)
    keep = valid & first & (x != blank)
    # stable compaction
    order = jnp.argsort(jnp.where(keep, 0, 1) * m +
                        jnp.arange(m)[None, :], axis=1)
    gathered = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(jnp.arange(m)[None, :] < new_len[:, None], gathered, 0)
    return out, new_len


def mean_iou(predictions, labels, num_classes: int):
    """Mean intersection-over-union over classes (mean_iou_op.h).
    Returns (mean_iou scalar, out_wrong [C], out_correct [C])."""
    p = jnp.asarray(predictions).reshape(-1)
    l = jnp.asarray(labels).reshape(-1)  # noqa: E741
    hit = (p == l)
    correct = jax.ops.segment_sum(hit.astype(jnp.int32), l, num_classes)
    pred_cnt = jax.ops.segment_sum(jnp.ones_like(p, jnp.int32), p,
                                   num_classes)
    label_cnt = jax.ops.segment_sum(jnp.ones_like(l, jnp.int32), l,
                                    num_classes)
    union = pred_cnt + label_cnt - correct
    present = union > 0
    iou = jnp.where(present, correct / jnp.maximum(union, 1), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    wrong = label_cnt - correct
    return miou.astype(jnp.float32), wrong, correct


def precision_recall(predictions, labels, num_classes: int,
                     weights=None, states=None):
    """Multi-class precision/recall/F1 (precision_recall_op.h).

    predictions: [N, C] scores or [N] class ids; labels [N].
    Returns (batch_metrics [6], accum_metrics [6], accum_states [C, 4])
    where metrics = (macro-P, macro-R, macro-F1, micro-P, micro-R,
    micro-F1) and states rows are (TP, FP, TN, FN) per class.
    """
    p = jnp.asarray(predictions)
    if p.ndim == 2:
        p = jnp.argmax(p, axis=1)
    l = jnp.asarray(labels).reshape(-1)  # noqa: E741
    w = jnp.asarray(weights).reshape(-1) if weights is not None else \
        jnp.ones_like(p, jnp.float32)
    ids = jnp.arange(num_classes)
    pred_onehot = (p[:, None] == ids[None, :]).astype(jnp.float32) * \
        w[:, None]
    label_onehot = (l[:, None] == ids[None, :]).astype(jnp.float32) * \
        w[:, None]
    tp = (pred_onehot * label_onehot).sum(0)
    fp = (pred_onehot * (1 - label_onehot)).sum(0)
    fn = ((1 - pred_onehot) * label_onehot).sum(0)
    tn = w.sum() - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)
    total = batch_states if states is None else \
        batch_states + jnp.asarray(states)

    def metrics(st):
        tp_, fp_, _, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-9),
                         0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-9),
                        0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, 1e-9), 0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = stp / jnp.maximum(stp + sfp, 1e-9)
        mr = stp / jnp.maximum(stp + sfn, 1e-9)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr,
                                                              1e-9), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return metrics(batch_states), metrics(total), total


def chunk_eval(inference, label, lengths, chunk_scheme: str = "IOB",
               num_chunk_types: int = 1, excluded_chunk_types=()):
    """Chunking precision/recall/F1 over IOB/IOE/IOBES tags
    (chunk_eval_op.h). Host-side eager op (dynamic chunk counts).
    Returns (precision, recall, f1, num_infer, num_label, num_correct).
    """
    inf = np.asarray(inference)
    lab = np.asarray(label)
    lens = np.asarray(lengths).reshape(-1)

    def extract(tags, ln):
        """Decode chunks [(start, end, type)] from tag ids.
        Tag layout (reference): tag = type * n_parts + part, where parts
        follow the scheme order (IOB: B=0, I=1; O = n_types*n_parts)."""
        parts = {"IOB": 2, "IOE": 2, "IOBES": 4}[chunk_scheme]
        chunks = []
        start, ctype = None, None
        for i in range(ln):
            t = int(tags[i])
            if t >= num_chunk_types * parts:  # outside
                if start is not None:
                    chunks.append((start, i - 1, ctype))
                    start = None
                continue
            ty, part = divmod(t, parts)
            begin = part == 0 if chunk_scheme != "IOE" else False
            if chunk_scheme == "IOBES" and part in (0, 3):
                begin = True
            if start is None or begin or ty != ctype:
                if start is not None:
                    chunks.append((start, i - 1, ctype))
                start, ctype = i, ty
            # end tags close the chunk at this position (IOE: E=1;
            # IOBES: E=1, S=3)
            ends = {"IOE": (1,), "IOBES": (1, 3)}.get(chunk_scheme, ())
            if part in ends and start is not None:
                chunks.append((start, i, ctype))
                start = None
        if start is not None:
            chunks.append((start, ln - 1, ctype))
        return {c for c in chunks if c[2] not in excluded_chunk_types}

    n_inf = n_lab = n_cor = 0
    for row in range(inf.shape[0]):
        ci = extract(inf[row], int(lens[row]))
        cl = extract(lab[row], int(lens[row]))
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    return (np.float32(prec), np.float32(rec), np.float32(f1),
            np.int64(n_inf), np.int64(n_lab), np.int64(n_cor))


def detection_map(detections, gt_boxes, gt_labels, class_num: int,
                  overlap_threshold: float = 0.5,
                  ap_type: str = "integral"):
    """Detection mAP (detection_map_op.h), host-side eager.

    detections: [M, 6] rows (label, score, x1, y1, x2, y2);
    gt_boxes: [G, 4]; gt_labels: [G]. Single-image/accumulated form.
    """
    det = np.asarray(detections, np.float32)
    gtb = np.asarray(gt_boxes, np.float32)
    gtl = np.asarray(gt_labels).reshape(-1)

    def iou(a, b):
        ix1 = np.maximum(a[0], b[:, 0])
        iy1 = np.maximum(a[1], b[:, 1])
        ix2 = np.minimum(a[2], b[:, 2])
        iy2 = np.minimum(a[3], b[:, 3])
        iw = np.maximum(ix2 - ix1, 0)
        ih = np.maximum(iy2 - iy1, 0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]) - inter)
        return inter / np.maximum(ua, 1e-9)

    aps = []
    for c in range(class_num):
        gt_c = gtb[gtl == c]
        det_c = det[det[:, 0] == c]
        if len(gt_c) == 0:
            continue
        order = np.argsort(-det_c[:, 1])
        det_c = det_c[order]
        matched = np.zeros(len(gt_c), bool)
        tp = np.zeros(len(det_c))
        fp = np.zeros(len(det_c))
        for i, d in enumerate(det_c):
            if len(gt_c) == 0:
                fp[i] = 1
                continue
            ious = iou(d[2:6], gt_c)
            j = int(np.argmax(ious))
            if ious[j] >= overlap_threshold and not matched[j]:
                tp[i] = 1
                matched[j] = True
            else:
                fp[i] = 1
        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        rec = ctp / len(gt_c)
        prec = ctp / np.maximum(ctp + cfp, 1e-9)
        if ap_type == "11point":
            ap = np.mean([prec[rec >= t].max() if (rec >= t).any() else 0.0
                          for t in np.linspace(0, 1, 11)])
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for r, p in zip(rec, prec):
                ap += (r - prev_r) * p
                prev_r = r
        aps.append(ap)
    return np.float32(np.mean(aps) if aps else 0.0)


def positive_negative_pair(score, label, query_ids):
    """Pairwise ranking quality per query (positive_negative_pair_op.h):
    counts correctly-ordered / wrongly-ordered / neutral pairs.
    Returns (positive, negative, neutral) float scalars."""
    s = np.asarray(score).reshape(-1)
    l = np.asarray(label).reshape(-1)  # noqa: E741
    q = np.asarray(query_ids).reshape(-1)
    pos = neg = neu = 0.0
    for qid in np.unique(q):
        idx = np.nonzero(q == qid)[0]
        for a in range(len(idx)):
            for b in range(a + 1, len(idx)):
                i, j = idx[a], idx[b]
                if l[i] == l[j]:
                    continue
                hi, lo = (i, j) if l[i] > l[j] else (j, i)
                if s[hi] > s[lo]:
                    pos += 1
                elif s[hi] < s[lo]:
                    neg += 1
                else:
                    neu += 1
    return (np.float32(pos), np.float32(neg), np.float32(neu))
