"""Operator registry.

TPU-native equivalent of the reference's op registry/metadata system
(reference: paddle/fluid/framework/op_registry.h:278 REGISTER_OPERATOR,
op_info.h). Both execution paths share one kernel set the way the
reference's dygraph and static modes share OperatorWithKernel::AllOpKernels
(paddle/fluid/imperative/prepared_operator.cc:147): here the "kernel" is a
pure jax function; the eager path wraps it with Tensor unwrap + autograd
tape, the traced path calls it raw under jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


@dataclass
class OpDef:
    name: str
    fn: Callable  # pure jax function
    module: str = ""
    differentiable: bool = True
    dynamic_shape: bool = False  # eager-only ops (nonzero, unique, ...)
    extra: Dict[str, Any] = field(default_factory=dict)


_REGISTRY: Dict[str, OpDef] = {}


def register_op(name: str, fn: Optional[Callable] = None, *,
                differentiable: bool = True, dynamic_shape: bool = False,
                module: str = "") -> Callable:
    def deco(f: Callable) -> Callable:
        _REGISTRY[name] = OpDef(name, f, module or f.__module__,
                                differentiable, dynamic_shape)
        return f
    if fn is not None:
        return deco(fn)
    return deco


def get_op(name: str) -> OpDef:
    from ..core.enforce import NotFoundError
    if name not in _REGISTRY:
        raise NotFoundError(f"Op {name!r} is not registered")
    return _REGISTRY[name]


def has_op(name: str) -> bool:
    return name in _REGISTRY


def all_ops() -> Dict[str, OpDef]:
    return dict(_REGISTRY)
