"""Search/sort ops (pure functional).

Reference parity: python/paddle/tensor/search.py (argmax, argsort, topk,
sort, index_sample, kthvalue, mode, searchsorted, bucketize).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None
                     else False)
    return out.astype(jnp.dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None
                     else False)
    return out.astype(jnp.dtype(dtype))


def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out


def sort(x, axis=-1, descending=False, stable=True):
    out = jnp.sort(x, axis=axis, stable=stable, descending=descending)
    return out


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if axis is None:
        axis = -1
    x_moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, inds = jax.lax.top_k(x_moved, k)
    else:
        vals, inds = jax.lax.top_k(-x_moved, k)
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(inds, -1, axis).astype(jnp.int32))


def kthvalue(x, k, axis=-1, keepdim=False):
    x_moved = jnp.moveaxis(x, axis, -1)
    vals = jnp.sort(x_moved, axis=-1)[..., k - 1]
    inds = jnp.argsort(x_moved, axis=-1, stable=True)[..., k - 1]
    if keepdim:
        vals = jnp.expand_dims(jnp.moveaxis(vals, -1, -1), axis)
        inds = jnp.expand_dims(inds, axis)
        return vals, inds.astype(jnp.int32)
    return vals, inds.astype(jnp.int32)


def mode(x, axis=-1, keepdim=False):
    # counts by pairwise equality (static-shape friendly)
    xm = jnp.moveaxis(x, axis, -1)
    eq = (xm[..., :, None] == xm[..., None, :]).sum(-1)
    idx = jnp.argmax(eq, axis=-1)
    vals = jnp.take_along_axis(xm, idx[..., None], axis=-1)[..., 0]
    if keepdim:
        return jnp.expand_dims(vals, axis), jnp.expand_dims(
            idx, axis).astype(jnp.int32)
    return vals, idx.astype(jnp.int32)


def index_sample(x, index):
    """Per-row gather (reference index_sample_op): out[i,j] = x[i, index[i,j]]."""
    return jnp.take_along_axis(x, index, axis=1)


def searchsorted(sorted_sequence, values, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        return jnp.searchsorted(sorted_sequence, values, side=side).astype(
            jnp.int32)
    fn = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))
    flat_seq = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
    flat_val = values.reshape(-1, values.shape[-1])
    return fn(flat_seq, flat_val).reshape(values.shape).astype(jnp.int32)


def bucketize(x, sorted_sequence, right=False):
    return jnp.searchsorted(sorted_sequence, x,
                            side="right" if right else "left").astype(
                                jnp.int32)
