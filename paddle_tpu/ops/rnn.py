"""Recurrent ops via lax.scan (pure functional).

Reference parity: python/paddle/nn/layer/rnn.py RNN/LSTM/GRU semantics
(operators/rnn_op + cudnn_lstm in the reference — here a single scan that
XLA unrolls/pipelines on TPU; gate order i,f,g,o like the reference's LSTM).

Weights per (layer, direction): [w_ih, w_hh, b_ih, b_hh] with
w_ih: [G*H, in], w_hh: [G*H, H] (G=1 simple, 3 gru, 4 lstm).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _cell_simple(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    return act(x @ w_ih.T + b_ih + h @ w_hh.T + b_hh)


def _cell_lstm(x, hc, w_ih, w_hh, b_ih, b_hh):
    h, c = hc
    gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _cell_gru(x, h, w_ih, w_hh, b_ih, b_hh):
    # gate order r, z, n (reference GRUCell)
    gi = x @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return (1.0 - z) * n + z * h


def _scan_direction(x_tbi, h0, weights, mode, activation, reverse=False):
    w_ih, w_hh, b_ih, b_hh = weights

    if mode == "LSTM":
        def step(carry, xt):
            new = _cell_lstm(xt, carry, w_ih, w_hh, b_ih, b_hh)
            return new, new[0]
    elif mode == "GRU":
        def step(carry, xt):
            new = _cell_gru(xt, carry, w_ih, w_hh, b_ih, b_hh)
            return new, new
    else:
        def step(carry, xt):
            new = _cell_simple(xt, carry, w_ih, w_hh, b_ih, b_hh, activation)
            return new, new

    final, outs = jax.lax.scan(step, h0, x_tbi, reverse=reverse)
    return final, outs


def rnn(x, initial_states, weights: Sequence, mode: str = "LSTM",
        num_layers: int = 1, direction: str = "forward",
        activation: str = "tanh", time_major: bool = False):
    """Multi-layer (bi)directional recurrence.

    x: [B, T, I] (or [T, B, I] when time_major). weights: flat list of
    4 arrays per (layer, direction). Returns (outputs, final_states):
    final_states shaped [num_layers*num_dirs, B, H] (tuple of h, c for
    LSTM), matching the reference RNN API.
    """
    bidirect = direction in ("bidirect", "bidirectional")
    num_dirs = 2 if bidirect else 1
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # [T, B, I]
    b = x.shape[1]

    h0c0 = initial_states
    finals_h: List = []
    finals_c: List = []
    layer_in = x
    for layer in range(num_layers):
        outs_dirs = []
        for d in range(num_dirs):
            idx = layer * num_dirs + d
            w = weights[idx * 4:(idx + 1) * 4]
            if mode == "LSTM":
                h_init = (h0c0[0][idx], h0c0[1][idx])
            else:
                h_init = h0c0[idx]
            final, outs = _scan_direction(layer_in, h_init, w, mode,
                                          activation, reverse=(d == 1))
            if mode == "LSTM":
                finals_h.append(final[0])
                finals_c.append(final[1])
            else:
                finals_h.append(final)
            outs_dirs.append(outs)
        layer_in = outs_dirs[0] if num_dirs == 1 else jnp.concatenate(
            outs_dirs, axis=-1)
    outputs = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
    h_stack = jnp.stack(finals_h, axis=0)
    if mode == "LSTM":
        return outputs, (h_stack, jnp.stack(finals_c, axis=0))
    return outputs, h_stack


def simple_rnn_cell(x, h, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    return _cell_simple(x, h, w_ih, w_hh, b_ih, b_hh, activation)


def lstm_cell(x, h, c, w_ih, w_hh, b_ih, b_hh):
    return _cell_lstm(x, (h, c), w_ih, w_hh, b_ih, b_hh)


def gru_cell(x, h, w_ih, w_hh, b_ih, b_hh):
    return _cell_gru(x, h, w_ih, w_hh, b_ih, b_hh)
