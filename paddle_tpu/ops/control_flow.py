"""Control-flow ops for traced mode.

Reference parity: paddle/fluid/operators/controlflow/ (while_op.cc,
conditional_block_op.cc) + python layers/control_flow.py (While, cond,
case, switch_case). TPU-native: jax.lax primitives — compiler-friendly
control flow that stays inside one XLA program instead of the reference's
sub-block interpretation.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..tensor import Tensor


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, tree)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """reference: paddle.static.nn.cond / conditional_block_op."""
    raw_pred = pred.value if isinstance(pred, Tensor) else pred
    raw_ops = _unwrap(operands)

    def tf(ops):
        return _unwrap(true_fn(*_wrap(ops)))

    def ff(ops):
        return _unwrap(false_fn(*_wrap(ops)))

    out = jax.lax.cond(raw_pred, tf, ff, raw_ops)
    return _wrap(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars):
    """reference: paddle.static.nn.while_loop / while_op.cc."""
    raw = _unwrap(loop_vars)

    def c(vs):
        out = cond_fn(*_wrap(vs))
        return out.value if isinstance(out, Tensor) else out

    def b(vs):
        return _unwrap(body_fn(*_wrap(vs)))

    out = jax.lax.while_loop(c, b, raw)
    return _wrap(out)


def fori_loop(lower, upper, body_fn: Callable, init):
    raw = _unwrap(init)

    def b(i, vs):
        return _unwrap(body_fn(i, _wrap(vs)))

    return _wrap(jax.lax.fori_loop(lower, upper, b, raw))


def scan(f: Callable, init, xs, length=None, reverse=False):
    """Structured loop with stacked outputs — the TPU-friendly replacement
    for unrolled RNN-style while loops."""
    raw_init = _unwrap(init)
    raw_xs = _unwrap(xs)

    def step(carry, x):
        c, y = f(_wrap(carry), _wrap(x))
        return _unwrap(c), _unwrap(y)

    carry, ys = jax.lax.scan(step, raw_init, raw_xs, length=length,
                             reverse=reverse)
    return _wrap(carry), _wrap(ys)


def case(pred_fn_pairs: Sequence, default: Callable = None):
    """reference: layers/control_flow.py case — first true pred wins."""
    preds = [p.value if isinstance(p, Tensor) else p
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]

    idx = jnp.argmax(jnp.stack([jnp.asarray(p, bool) for p in preds]))
    any_true = jnp.any(jnp.stack([jnp.asarray(p, bool) for p in preds]))
    branch = jnp.where(any_true, idx, len(fns))

    def mk(fn):
        return lambda _: _unwrap(fn())

    out = jax.lax.switch(branch, [mk(f) for f in fns] + [mk(default)],
                         None)
    return _wrap(out)


def switch_case(branch_index, branch_fns, default: Callable = None):
    """reference: layers/control_flow.py switch_case."""
    raw_idx = branch_index.value if isinstance(branch_index, Tensor) else \
        jnp.asarray(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map arbitrary keys to dense branch ids
        table = jnp.asarray(keys)
        dense = jnp.argmax(table == raw_idx)
        in_table = jnp.any(table == raw_idx)
    else:
        fns = list(branch_fns)
        dense = raw_idx
        in_table = (raw_idx >= 0) & (raw_idx < len(fns))
    if default is None:
        default = fns[-1]

    def mk(fn):
        return lambda _: _unwrap(fn())

    branch = jnp.where(in_table, dense, len(fns))
    out = jax.lax.switch(branch, [mk(f) for f in fns] + [mk(default)], None)
    return _wrap(out)


# --------------------------------------------------------------------------
# TensorArray (reference: framework/lod_tensor_array.h:22 LoDTensorArray,
# layers/control_flow.py:1459 array_write/array_read/array_length,
# operators/array_to_lod_tensor_op.cc, tensor_array_to_tensor_op.cc,
# controlflow/while_op.cc consumption). Dual mode:
# - eager: plain python-list semantics (the reference's dygraph
#   LoDTensorArray IS a list).
# - traced: a fixed-capacity ring of one stacked buffer + a length
#   scalar, registered as a jax pytree so it threads through
#   lax.while_loop / lax.cond bodies; writes lower to
#   dynamic_update_index (static shapes, XLA-friendly).
# --------------------------------------------------------------------------

class TensorArray:
    """Dynamic tensor collection; traced mode needs `capacity`."""

    def __init__(self, items=None, capacity: int = 0, example=None):
        self._items: List[Any] = list(items) if items else []
        self._buf = None
        self._len = None
        if capacity:
            if example is None:
                raise ValueError(
                    "traced TensorArray needs an example element for "
                    "shape/dtype (static shapes under jit)")
            ex = example.value if isinstance(example, Tensor) else \
                jnp.asarray(example)
            self._buf = jnp.zeros((capacity,) + ex.shape, ex.dtype)
            self._len = jnp.zeros((), jnp.int32)

    # -- traced state as a pytree -------------------------------------
    def _tree_flatten(self):
        if self._buf is not None:
            return (self._buf, self._len), ("traced",)
        return tuple(self._items), ("eager",)

    @classmethod
    def _tree_unflatten(cls, aux, children):
        ta = cls.__new__(cls)
        if aux[0] == "traced":
            ta._items = []
            ta._buf, ta._len = children
        else:
            ta._items = list(children)
            ta._buf = None
            ta._len = None
        return ta

    @property
    def traced(self) -> bool:
        return self._buf is not None

    def __len__(self):
        if self.traced:
            return int(self._len)
        return len(self._items)


jax.tree_util.register_pytree_node(
    TensorArray,
    lambda ta: ta._tree_flatten(),
    lambda aux, children: TensorArray._tree_unflatten(aux, children))


def create_array(dtype="float32", initialized_list=None) -> TensorArray:
    """reference: paddle.tensor.create_array (fluid/layers/tensor.py)."""
    return TensorArray(initialized_list)


def array_write(x, i, array: TensorArray = None) -> TensorArray:
    """reference: layers/control_flow.py:1459 array_write — write x at
    index i (eager list append/replace; traced dynamic_update_index)."""
    if array is None:
        array = TensorArray()
    raw = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if array.traced:
        idx = i.value if isinstance(i, Tensor) else jnp.asarray(i)
        idx = idx.astype(jnp.int32).reshape(())
        out = TensorArray.__new__(TensorArray)
        out._items = []
        out._buf = jax.lax.dynamic_update_index_in_dim(
            array._buf, raw.astype(array._buf.dtype), idx, 0)
        out._len = jnp.maximum(array._len, idx + 1)
        return out
    idx = int(i.value if isinstance(i, Tensor) else i)
    while len(array._items) <= idx:
        array._items.append(None)
    array._items[idx] = Tensor(raw)
    return array


def array_read(array: TensorArray, i):
    """reference: layers/control_flow.py array_read."""
    if array.traced:
        idx = i.value if isinstance(i, Tensor) else jnp.asarray(i)
        return Tensor(jax.lax.dynamic_index_in_dim(
            array._buf, idx.astype(jnp.int32).reshape(()), 0,
            keepdims=False))
    return array._items[int(i.value if isinstance(i, Tensor) else i)]


def array_length(array: TensorArray):
    """reference: layers/control_flow.py array_length /
    lod_array_length_op.cc."""
    if array.traced:
        return Tensor(array._len)
    return Tensor(jnp.asarray(len(array._items), jnp.int32))


def tensor_array_to_tensor(array: TensorArray, axis: int = 0,
                           use_stack: bool = False):
    """reference: tensor_array_to_tensor_op.cc — concat (or stack) the
    written elements. Traced mode returns the full-capacity stack and the
    valid length (static shapes); eager concatenates exactly the written
    items. Returns (tensor, index/length info)."""
    if array.traced:
        if use_stack:
            out = jnp.moveaxis(array._buf, 0, axis)
        elif axis == 0:
            out = jnp.reshape(array._buf,
                              (-1,) + array._buf.shape[2:])
        else:
            raise NotImplementedError(
                "traced tensor_array_to_tensor supports axis=0 concat")
        return Tensor(out), Tensor(array._len)
    gaps = [i for i, t in enumerate(array._items) if t is None]
    if gaps:
        raise ValueError(
            f"tensor_array_to_tensor: uninitialized slots {gaps} "
            "(array_write skipped those indices)")
    vals = [t.value if isinstance(t, Tensor) else jnp.asarray(t)
            for t in array._items]
    sizes = jnp.asarray([v.shape[axis] if not use_stack else 1
                         for v in vals], jnp.int32)
    out = jnp.stack(vals, axis=axis) if use_stack else \
        jnp.concatenate(vals, axis=axis)
    return Tensor(out), Tensor(sizes)


def array_to_lod_tensor(array: TensorArray, table=None):
    """reference: array_to_lod_tensor_op.cc — collapse a TensorArray to
    one ragged batch (RaggedTensor analog of LoDTensor)."""
    from ..framework.ragged import RaggedTensor
    if array.traced:
        items = [array._buf[i] for i in range(int(array._len))]
    else:
        gaps = [i for i, t in enumerate(array._items) if t is None]
        if gaps:
            raise ValueError(
                f"array_to_lod_tensor: uninitialized slots {gaps}")
        items = array._items
    vals = [t.value if isinstance(t, Tensor) else jnp.asarray(t)
            for t in items]
    return RaggedTensor.from_rows(vals)


def lod_tensor_to_array(x, table=None) -> TensorArray:
    """reference: lod_tensor_to_array_op.cc — split a ragged batch into a
    TensorArray, one row group per entry."""
    from ..framework.ragged import RaggedTensor
    if isinstance(x, RaggedTensor):
        rows = x.rows()
    else:
        raw = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        rows = [raw[i] for i in range(raw.shape[0])]
    return TensorArray([Tensor(jnp.asarray(r)) for r in rows])


def Assert(cond, data=None, summarize=20):  # noqa: N802 - reference name
    """reference: operators/controlflow/assert_op.cc
    (paddle.static.nn.control_flow.Assert). Eager: raises immediately on
    a false condition; under a trace the check is skipped (the reference
    op only runs in executor mode — XLA programs have no host assert)."""
    raw = cond.value if isinstance(cond, Tensor) else cond
    try:
        ok = bool(jnp.all(raw))
    except jax.errors.TracerBoolConversionError:
        return None
    if not ok:
        shown = []
        for d in (data or []):
            v = d.value if isinstance(d, Tensor) else d
            shown.append(jnp.ravel(jnp.asarray(v))[:summarize])
        raise AssertionError(f"Assert failed; data={shown}")
    return None
