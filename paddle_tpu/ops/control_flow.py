"""Control-flow ops for traced mode.

Reference parity: paddle/fluid/operators/controlflow/ (while_op.cc,
conditional_block_op.cc) + python layers/control_flow.py (While, cond,
case, switch_case). TPU-native: jax.lax primitives — compiler-friendly
control flow that stays inside one XLA program instead of the reference's
sub-block interpretation.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..tensor import Tensor


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, tree)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """reference: paddle.static.nn.cond / conditional_block_op."""
    raw_pred = pred.value if isinstance(pred, Tensor) else pred
    raw_ops = _unwrap(operands)

    def tf(ops):
        return _unwrap(true_fn(*_wrap(ops)))

    def ff(ops):
        return _unwrap(false_fn(*_wrap(ops)))

    out = jax.lax.cond(raw_pred, tf, ff, raw_ops)
    return _wrap(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars):
    """reference: paddle.static.nn.while_loop / while_op.cc."""
    raw = _unwrap(loop_vars)

    def c(vs):
        out = cond_fn(*_wrap(vs))
        return out.value if isinstance(out, Tensor) else out

    def b(vs):
        return _unwrap(body_fn(*_wrap(vs)))

    out = jax.lax.while_loop(c, b, raw)
    return _wrap(out)


def fori_loop(lower, upper, body_fn: Callable, init):
    raw = _unwrap(init)

    def b(i, vs):
        return _unwrap(body_fn(i, _wrap(vs)))

    return _wrap(jax.lax.fori_loop(lower, upper, b, raw))


def scan(f: Callable, init, xs, length=None, reverse=False):
    """Structured loop with stacked outputs — the TPU-friendly replacement
    for unrolled RNN-style while loops."""
    raw_init = _unwrap(init)
    raw_xs = _unwrap(xs)

    def step(carry, x):
        c, y = f(_wrap(carry), _wrap(x))
        return _unwrap(c), _unwrap(y)

    carry, ys = jax.lax.scan(step, raw_init, raw_xs, length=length,
                             reverse=reverse)
    return _wrap(carry), _wrap(ys)


def case(pred_fn_pairs: Sequence, default: Callable = None):
    """reference: layers/control_flow.py case — first true pred wins."""
    preds = [p.value if isinstance(p, Tensor) else p
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]

    idx = jnp.argmax(jnp.stack([jnp.asarray(p, bool) for p in preds]))
    any_true = jnp.any(jnp.stack([jnp.asarray(p, bool) for p in preds]))
    branch = jnp.where(any_true, idx, len(fns))

    def mk(fn):
        return lambda _: _unwrap(fn())

    out = jax.lax.switch(branch, [mk(f) for f in fns] + [mk(default)],
                         None)
    return _wrap(out)


def switch_case(branch_index, branch_fns, default: Callable = None):
    """reference: layers/control_flow.py switch_case."""
    raw_idx = branch_index.value if isinstance(branch_index, Tensor) else \
        jnp.asarray(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map arbitrary keys to dense branch ids
        table = jnp.asarray(keys)
        dense = jnp.argmax(table == raw_idx)
        in_table = jnp.any(table == raw_idx)
    else:
        fns = list(branch_fns)
        dense = raw_idx
        in_table = (raw_idx >= 0) & (raw_idx < len(fns))
    if default is None:
        default = fns[-1]

    def mk(fn):
        return lambda _: _unwrap(fn())

    branch = jnp.where(in_table, dense, len(fns))
    out = jax.lax.switch(branch, [mk(f) for f in fns] + [mk(default)], None)
    return _wrap(out)
