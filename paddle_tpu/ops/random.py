"""Random ops (pure functional — explicit key in, plus eager wrappers that
draw from the global Generator in paddle_tpu.core.rng).

Reference parity: python/paddle/tensor/random.py (uniform, normal, randn,
randint, randperm, bernoulli, multinomial, poisson, exponential).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, default_dtype
from ..core.rng import next_key


def _key(key):
    return key if key is not None else next_key()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0,  # noqa: A002
            key=None):
    # reference: uniform(shape, dtype, min, max, seed); a nonzero int
    # seed pins the draw (key= stays the explicit functional override)
    if isinstance(seed, int) and seed and key is None:
        import jax as _jax
        key = _jax.random.PRNGKey(seed)
    dtype = convert_dtype(dtype) if dtype else default_dtype()
    return jax.random.uniform(_key(key), tuple(shape), dtype=dtype,
                              minval=min, maxval=max)


def normal(mean=0.0, std=1.0, shape=None, dtype=None, key=None):
    dtype = convert_dtype(dtype) if dtype else default_dtype()
    return mean + std * jax.random.normal(_key(key), tuple(shape or ()),
                                          dtype=dtype)


def randn(shape, dtype=None, key=None):
    return normal(0.0, 1.0, shape, dtype, key)


def rand(shape, dtype=None, key=None):
    return uniform(shape, dtype, 0.0, 1.0, key=key)


def randint(low=0, high=None, shape=(1,), dtype="int64", key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(key), tuple(shape), low, high,
                              dtype=convert_dtype(dtype))


def randint_like(x, low=0, high=None, dtype=None, key=None):
    return randint(low, high, x.shape, dtype or x.dtype, key)


def randperm(n, dtype="int64", key=None):
    return jax.random.permutation(_key(key), n).astype(convert_dtype(dtype))


def shuffle(x, axis=0, key=None):
    return jax.random.permutation(_key(key), x, axis=axis,
                                  independent=False)


def bernoulli(x, key=None):
    return jax.random.bernoulli(_key(key), x).astype(x.dtype)


def multinomial(x, num_samples=1, replacement=False, key=None):
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    if replacement:
        return jax.random.categorical(
            _key(key), logits, axis=-1,
            shape=(*x.shape[:-1], num_samples)).astype(jnp.int32)
    # Without replacement: Gumbel top-k trick.
    g = jax.random.gumbel(_key(key), x.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int32)


def poisson(x, key=None):
    return jax.random.poisson(_key(key), x).astype(x.dtype)


def exponential(x, lam=1.0, key=None):
    return (jax.random.exponential(_key(key), x.shape, dtype=x.dtype) /
            lam)


def standard_gamma(alpha, key=None):
    return jax.random.gamma(_key(key), alpha)


def normal_like(x, mean=0.0, std=1.0, key=None):
    return normal(mean, std, x.shape, x.dtype, key)


def uniform_like(x, min=-1.0, max=1.0, key=None):  # noqa: A002
    return uniform(x.shape, x.dtype, min, max, key=key)


def rand_like(x, key=None):
    return rand(x.shape, x.dtype, key)


def gumbel(shape, dtype=None, key=None):
    dtype = convert_dtype(dtype) if dtype else default_dtype()
    return jax.random.gumbel(_key(key), tuple(shape), dtype=dtype)


def binomial(count, prob, key=None):
    """Sample Binomial(count, prob) elementwise (reference binomial op)."""
    c = jnp.asarray(count)
    p = jnp.asarray(prob)
    shape = jnp.broadcast_shapes(c.shape, p.shape)
    return jax.random.binomial(
        _key(key), c.astype(jnp.float32), p.astype(jnp.float32),
        shape=shape).astype(jnp.int32)


def lognormal(mean=1.0, std=2.0, shape=(1,), dtype=None, key=None):
    return jnp.exp(normal(mean, std, shape, dtype, key))



def standard_normal(shape, dtype=None, key=None):
    """N(0,1) samples (reference: paddle.standard_normal,
    tensor/random.py:220)."""
    return randn(shape, dtype, key)
