"""Extended vision / conv / CTR op family (pure functional).

Reference parity for kernels under paddle/fluid/operators/:
affine_channel_op.cc, space_to_depth_op.cc, shuffle_channel_op.cc,
row_conv_op.cc, conv_shift_op.cc, bilinear_tensor_product_op.cc,
add_position_encoding_op.cc, fsp_op.cc, im2sequence_op.cc,
partial_concat_op.cc, partial_sum_op.cc, shuffle_batch_op.cc,
batch_fc_op.cc, cvm_op.cc, unpool_op.cc, spp_op.cc,
detection/{psroi_pool_op.cc, prroi_pool_op.cc, yolov3_loss_op.h},
deformable_conv_op.cc (+ v1), conv_transpose_op.cc (3d),
correlation_op.cc.

All vectorized jax — gathers/scatters + einsum contractions instead of the
reference's per-element CUDA loops, so XLA tiles the contractions onto the
MXU and fuses the rest.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# --- channel/layout transforms ----------------------------------------------

def affine_channel(x, scale, bias, data_format="NCHW"):
    """Out = scale*x + bias per channel (affine_channel_op.cc)."""
    if x.ndim == 2:
        return x * scale.reshape(1, -1) + bias.reshape(1, -1)
    if data_format == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


def space_to_depth(x, blocksize):
    """YOLOv2 reorg (space_to_depth_op.cc): NCHW [N,C,H,W] ->
    [N, C*bs*bs, H/bs, W/bs]."""
    n, c, h, w = x.shape
    bs = blocksize
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


def shuffle_channel(x, group):
    """Channel shuffle (shuffle_channel_op.cc) — NCHW. Same math as
    nn_functional.channel_shuffle; reference-op-name spelling."""
    from .nn_functional import channel_shuffle
    return channel_shuffle(x, group)


def cvm(x, cvm_input, use_cvm=True):
    """CTR continuous-value-model feature transform (cvm_op.cc): first two
    columns are (show, click); use_cvm logs them, else they are dropped."""
    del cvm_input  # kept for input-signature parity; stats live in x[:, :2]
    if use_cvm:
        show = jnp.log(x[:, 0] + 1.0)
        click = jnp.log(x[:, 1] + 1.0) - show
        return jnp.concatenate([show[:, None], click[:, None], x[:, 2:]],
                               axis=1)
    return x[:, 2:]


def shuffle_batch(x, key=None):
    """Random permutation of rows (shuffle_batch_op.cc). Returns
    (shuffled, shuffle_idx)."""
    if key is None:
        from ..core.rng import next_key
        key = next_key()
    idx = jax.random.permutation(key, x.shape[0])
    return x[idx], idx


def _partial_slice(x, start_index, length):
    # reference semantics: negative start_index counts from the end
    start = start_index + x.shape[1] if start_index < 0 else start_index
    end = x.shape[1] if length < 0 else start + length
    return x[:, start:end]


def partial_concat(xs, start_index=0, length=-1):
    """Concat a column slice of each input (partial_concat_op.cc)."""
    return jnp.concatenate(
        [_partial_slice(x, start_index, length) for x in xs], axis=1)


def partial_sum(xs, start_index=0, length=-1):
    """Sum a column slice of each input (partial_sum_op.cc)."""
    out = None
    for x in xs:
        piece = _partial_slice(x, start_index, length)
        out = piece if out is None else out + piece
    return out


def batch_fc(x, w, bias=None):
    """Per-slot batched FC (batch_fc_op.cc): x [S, N, Din], w [S, Din, Dout],
    bias [S, Dout] -> [S, N, Dout]."""
    out = jnp.einsum("snd,sde->sne", x, w)
    if bias is not None:
        out = out + bias[:, None, :]
    return out


# --- sequence-ish convs -----------------------------------------------------

def row_conv(x, weight):
    """Lookahead (row) convolution for DeepSpeech2 (row_conv_op.cc):
    x [N, T, D], weight [context, D]; out[t] = sum_j w[j]*x[t+j]."""
    ctx = weight.shape[0]
    n, t, d = x.shape
    padded = jnp.pad(x, ((0, 0), (0, ctx - 1), (0, 0)))
    idx = jnp.arange(t)[:, None] + jnp.arange(ctx)[None, :]   # [T, ctx]
    windows = padded[:, idx]                                   # [N, T, ctx, D]
    return jnp.einsum("ntcd,cd->ntd", windows, weight)


def conv_shift(x, y):
    """Circular convolution (conv_shift_op.cc): x [B, M], y [B, N] with N
    odd; out[i,j] = sum_k x[i, (j - N/2 + k) mod M] * y[i, k]."""
    m = x.shape[1]
    nk = y.shape[1]
    half = nk // 2
    j = jnp.arange(m)[:, None]
    k = jnp.arange(nk)[None, :]
    gather = (j - half + k) % m                                # [M, N]
    return jnp.einsum("bmn,bn->bm", x[:, gather], y)


def im2sequence(x, kernels, strides=(1, 1), paddings=(0, 0, 0, 0)):
    """Sliding-window im2col to a sequence (im2sequence_op.cc):
    x [N, C, H, W] -> [N*out_h*out_w, C*kh*kw] row-major over windows.
    Thin wrapper over nn_functional.unfold (one im2col implementation)."""
    from .nn_functional import unfold
    pu, pl, pd, pr = paddings
    x = jnp.pad(x, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
    n, c, _h, _w = x.shape
    kh, kw = kernels
    cols = unfold(x, kernels, strides)          # [N, C*kh*kw, oh*ow]
    return cols.transpose(0, 2, 1).reshape(-1, c * kh * kw)


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """out = alpha*x + beta*sinusoidal_PE (add_position_encoding_op.cc);
    x [B, T, D]. PE matches the reference kernel: first half sin, second
    half cos, frequency indexed within each half."""
    _b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    i = jnp.arange(half, dtype=x.dtype)[None, :]
    div = jnp.power(10000.0, i / jnp.maximum(half - 1.0, 1.0))
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    if d % 2:
        pe = jnp.pad(pe, ((0, 0), (0, 1)))
    return alpha * x + beta * pe[None]


def fsp(x, y):
    """Flow-of-solution-procedure matrix for distillation (fsp_op.cc):
    x [N, C1, H, W], y [N, C2, H, W] -> [N, C1, C2] spatial-mean outer
    product."""
    h_w = x.shape[2] * x.shape[3]
    return jnp.einsum("nchw,ndhw->ncd", x, y) / h_w


def bilinear_tensor_product(x, y, weight, bias=None):
    """out[:, k] = x @ W_k @ y^T diag (bilinear_tensor_product_op.cc):
    weight [K, Dx, Dy]."""
    out = jnp.einsum("nd,kde,ne->nk", x, weight, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1=1,
                stride2=1, corr_type_multiply=1):
    """FlowNet correlation layer (correlation_op.cc): patch dot products
    over a displacement window; NCHW inputs. Only the kernel_size=1 case
    (the FlowNet configuration) is implemented."""
    del corr_type_multiply
    if kernel_size != 1:
        raise NotImplementedError("correlation: kernel_size != 1")
    n, c, h, w = x1.shape
    d = max_displacement
    x1p = jnp.pad(x1, ((0, 0), (0, 0), (pad_size,) * 2, (pad_size,) * 2))
    x2p = jnp.pad(x2, ((0, 0), (0, 0), (pad_size,) * 2, (pad_size,) * 2))
    outs = []
    for di in range(-(d // stride2), d // stride2 + 1):
        for dj in range(-(d // stride2), d // stride2 + 1):
            shifted = jnp.roll(x2p, (-di * stride2, -dj * stride2),
                               axis=(2, 3))
            prod = (x1p * shifted).mean(axis=1)                 # [N, H+2p, W+2p]
            outs.append(prod[:, pad_size:pad_size + h,
                             pad_size:pad_size + w])
    out = jnp.stack(outs, axis=1)                               # [N, G*G, H, W]
    if stride1 > 1:
        out = out[:, :, ::stride1, ::stride1]
    return out


# --- pooling extras ---------------------------------------------------------

def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    """Inverse of max_pool2d with indices (unpool_op.cc): scatter pooled
    values back to their argmax positions. x/indices [N, C, h, w]; indices
    are flat positions within each [H*W] input map."""
    if stride is None:
        stride = kernel_size
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    n, c, h, w = x.shape
    if output_size is None:
        oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
        ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
    else:
        oh, ow = output_size
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    flat = jax.vmap(jax.vmap(
        lambda dst, ind, src: dst.at[ind].set(src)))(
            flat, idx, x.reshape(n, c, -1))
    return flat.reshape(n, c, oh, ow)


unpool = max_unpool2d


def spp(x, pyramid_height, pooling_type="max"):
    """Spatial pyramid pooling (spp_op.cc): concat adaptive pools at bin
    resolutions 2^0..2^(L-1); NCHW -> [N, C*sum(4^l)]."""
    from .nn_functional import adaptive_avg_pool2d, adaptive_max_pool2d
    n = x.shape[0]
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        pooled = (adaptive_max_pool2d(x, bins) if pooling_type == "max"
                  else adaptive_avg_pool2d(x, bins))
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


def psroi_pool(x, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None):
    """Position-sensitive ROI average pooling (detection/psroi_pool_op.cc):
    x [N, output_channels*ph*pw, H, W], rois [R, 4] (x1,y1,x2,y2 in image
    coords), roi i taken from batch image given by rois_num mapping (or
    image 0 when None and N == 1)."""
    ph, pw = pooled_height, pooled_width
    n, ctot, h, w = x.shape
    del ctot
    batch_idx = _roi_batch_index(rois, rois_num, n)

    def one(roi, b):
        x1, y1, x2, y2 = roi * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1) / ph
        rw = jnp.maximum(x2 - x1, 0.1) / pw
        # reference channel layout (psroi_pool_op.cc): input channel for
        # (class c, bin i, j) is (c*ph + i)*pw + j — channel-major
        img = x[b].reshape(output_channels, ph, pw, h, w)
        out = jnp.zeros((output_channels, ph, pw), x.dtype)
        ys = jnp.arange(h, dtype=x.dtype)[:, None]
        xs = jnp.arange(w, dtype=x.dtype)[None, :]
        for i in range(ph):
            for j in range(pw):
                hs, he = y1 + i * rh, y1 + (i + 1) * rh
                ws, we = x1 + j * rw, x1 + (j + 1) * rw
                mask = ((ys >= jnp.floor(hs)) & (ys < jnp.ceil(he))
                        & (xs >= jnp.floor(ws)) & (xs < jnp.ceil(we)))
                area = jnp.maximum(mask.sum(), 1)
                chans = img[:, i, j]                           # [oc, H, W]
                val = jnp.where(mask[None], chans, 0.0).sum((1, 2)) / area
                out = out.at[:, i, j].set(val)
        return out

    return jax.vmap(one)(rois, batch_idx)


def _roi_batch_index(rois, rois_num, n_images):
    if rois_num is None:
        return jnp.zeros((rois.shape[0],), jnp.int32)
    # rois_num: [n_images] count per image -> per-roi image index
    return jnp.repeat(jnp.arange(n_images, dtype=jnp.int32), rois_num,
                      total_repeat_length=rois.shape[0])


def prroi_pool(x, rois, spatial_scale, pooled_height, pooled_width,
               rois_num=None, sampling=4):
    """Precise ROI pooling (detection/prroi_pool_op.cc). The reference
    integrates the bilinear surface exactly; here each bin averages a
    `sampling` x `sampling` grid of bilinear samples — the same estimator
    roi_align uses, converging to the precise integral as sampling grows."""
    ph, pw = pooled_height, pooled_width
    n, c, h, w = x.shape
    batch_idx = _roi_batch_index(rois, rois_num, n)

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        ly = jnp.clip(yy - y0, 0.0, 1.0)
        lx = jnp.clip(xx - x0, 0.0, 1.0)
        v = (img[:, y0, x0] * (1 - ly) * (1 - lx)
             + img[:, y1, x0] * ly * (1 - lx)
             + img[:, y0, x1] * (1 - ly) * lx
             + img[:, y1, x1] * ly * lx)
        return v

    s = sampling

    def one(roi, b):
        x1, y1, x2, y2 = roi * spatial_scale
        rh = (y2 - y1) / ph
        rw = (x2 - x1) / pw
        ii = jnp.arange(ph, dtype=x.dtype)
        jj = jnp.arange(pw, dtype=x.dtype)
        off = (jnp.arange(s, dtype=x.dtype) + 0.5) / s
        yy = y1 + (ii[:, None] + 0.0)[..., None] * rh + off[None, None] * rh
        xx = x1 + (jj[:, None] + 0.0)[..., None] * rw + off[None, None] * rw
        # [ph, s] x [pw, s] sample grids
        ys = yy.reshape(ph, 1, s, 1)
        xs = xx.reshape(1, pw, 1, s)
        ysb = jnp.broadcast_to(ys, (ph, pw, s, s)).reshape(-1)
        xsb = jnp.broadcast_to(xs, (ph, pw, s, s)).reshape(-1)
        vals = bilinear(x[b], ysb, xsb)                        # [C, ph*pw*s*s]
        vals = vals.reshape(c, ph, pw, s * s).mean(-1)
        return vals

    return jax.vmap(one)(rois, batch_idx)


# --- deformable conv --------------------------------------------------------

def deformable_conv(x, offset, weight, mask=None, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1, groups=1):
    """Deformable convolution v1/v2 (deformable_conv_op.cc,
    deformable_conv_v1_op.cc; v2 when mask given).

    x [N, C, H, W]; offset [N, 2*dg*kh*kw, Hout, Wout] ordered (y, x) per
    tap; mask [N, dg*kh*kw, Hout, Wout]; weight [Cout, C/groups, kh, kw].
    Implementation: gather bilinear samples per tap -> one einsum
    contraction (maps to the MXU), instead of the reference's per-element
    modulated_deformable_im2col CUDA kernel.
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    n, c, h, w = x.shape
    cout, _cpg, kh, kw = weight.shape
    oh = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    dg = deformable_groups
    kk = kh * kw

    off = offset.reshape(n, dg, kk, 2, oh, ow)
    base_y = (jnp.arange(oh) * s[0] - p[0])[:, None]           # [oh, 1]
    base_x = (jnp.arange(ow) * s[1] - p[1])[None, :]           # [1, ow]
    ky = (jnp.arange(kh) * d[0]).repeat(kw)                    # [kk]
    kx = jnp.tile(jnp.arange(kw) * d[1], kh)                   # [kk]
    # sample positions [N, dg, kk, oh, ow]
    yy = base_y[None, None, None] + ky[None, None, :, None, None] \
        + off[:, :, :, 0]
    xx = base_x[None, None, None] + kx[None, None, :, None, None] \
        + off[:, :, :, 1]

    y0 = jnp.floor(yy)
    x0 = jnp.floor(xx)
    ly = yy - y0
    lx = xx - x0

    def gather(py, px):
        pyc = jnp.clip(py.astype(jnp.int32), 0, h - 1)
        pxc = jnp.clip(px.astype(jnp.int32), 0, w - 1)
        valid = ((py >= 0) & (py <= h - 1) & (px >= 0)
                 & (px <= w - 1)).astype(x.dtype)
        # x [N, C, H, W] -> group channels by dg: [N, dg, C/dg, H, W]
        xg = x.reshape(n, dg, c // dg, h, w)
        flat = xg.reshape(n, dg, c // dg, h * w)
        ind = (pyc * w + pxc).reshape(n, dg, -1)               # [N,dg,kk*oh*ow]
        vals = jnp.take_along_axis(flat, ind[:, :, None, :], axis=3)
        vals = vals.reshape(n, dg, c // dg, kk, oh, ow)
        return vals * valid[:, :, None]

    v00 = gather(y0, x0) * ((1 - ly) * (1 - lx))[:, :, None]
    v01 = gather(y0, x0 + 1) * ((1 - ly) * lx)[:, :, None]
    v10 = gather(y0 + 1, x0) * (ly * (1 - lx))[:, :, None]
    v11 = gather(y0 + 1, x0 + 1) * (ly * lx)[:, :, None]
    sampled = v00 + v01 + v10 + v11        # [N, dg, C/dg, kk, oh, ow]
    if mask is not None:
        sampled = sampled * mask.reshape(n, dg, 1, kk, oh, ow)
    sampled = sampled.reshape(n, c, kk, oh, ow)

    wg = weight.reshape(groups, cout // groups, c // groups, kk)
    sg = sampled.reshape(n, groups, c // groups, kk, oh, ow)
    out = jnp.einsum("ngckhw,gock->ngohw", sg, wg)
    out = out.reshape(n, cout, oh, ow)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCDHW"):
    """3D transposed convolution (conv_transpose_op.cc conv3d_transpose):
    x [N, C, D, H, W], weight [Cin, Cout/g, kd, kh, kw]."""
    if output_size is not None:
        from .nn_functional import _out_padding_from_size
        sp = x.shape[1:4] if data_format == "NDHWC" else x.shape[2:5]
        output_padding = _out_padding_from_size(
            sp, output_size, stride, padding, dilation, weight.shape[2:5],
            3)
    if groups != 1:
        raise NotImplementedError("conv3d_transpose groups>1")
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    d = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    op = (output_padding,) * 3 if isinstance(output_padding, int) \
        else tuple(output_padding)
    if data_format == "NDHWC":
        x = x.transpose(0, 4, 1, 2, 3)
    # lax.conv_transpose with IOdhw weight layout
    pads = [(d[i] * (weight.shape[2 + i] - 1) - p[i],
             d[i] * (weight.shape[2 + i] - 1) - p[i] + op[i])
            for i in range(3)]
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(weight, (2, 3, 4)).swapaxes(0, 1),
        window_strides=(1, 1, 1), padding=pads,
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    if data_format == "NDHWC":
        out = out.transpose(0, 2, 3, 4, 1)
    return out


# --- YOLOv3 loss ------------------------------------------------------------

def _sce(x, label):
    """Elementwise sigmoid cross entropy (yolov3_loss_op.h SCE)."""
    from .nn_functional import _sigmoid_ce
    return _sigmoid_ce(x, label)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True):
    """YOLOv3 training loss (detection/yolov3_loss_op.h), fully vectorized.

    x: [N, A*(5+C), H, W] raw head output, A = len(anchor_mask);
    gt_box: [N, B, 4] normalized (cx, cy, w, h); gt_label: [N, B] int;
    anchors: flat list of all anchor (w, h) pairs; anchor_mask: indices of
    the anchors this head predicts. Returns per-image loss [N].
    """
    n, _, h, w = x.shape
    a = len(anchor_mask)
    cn = class_num
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    an_use = an_all[np.asarray(anchor_mask)]                  # [A, 2]
    input_size = downsample_ratio * h
    b = gt_box.shape[1]

    x = x.reshape(n, a, 5 + cn, h, w)
    px, py = x[:, :, 0], x[:, :, 1]                            # [N,A,H,W]
    pw, ph_ = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]                                         # [N,A,C,H,W]

    gx, gy = gt_box[..., 0], gt_box[..., 1]                    # [N,B]
    gw, gh = gt_box[..., 2], gt_box[..., 3]
    valid = (gw > 0) & (gh > 0)                                # [N,B]
    if gt_score is None:
        gt_score = jnp.ones_like(gx)

    # --- responsible anchor per gt: best shape-IoU over ALL anchors
    inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0]
                         / input_size)
             * jnp.minimum(gh[..., None], an_all[None, None, :, 1]
                           / input_size))
    union = (gw * gh)[..., None] + (an_all[None, None, :, 0]
                                    * an_all[None, None, :, 1]
                                    / input_size ** 2) - inter
    an_iou = inter / jnp.maximum(union, 1e-10)                 # [N,B,Atot]
    best_an = jnp.argmax(an_iou, axis=-1)                      # [N,B]
    mask_np = np.asarray(anchor_mask)
    # map best anchor -> local index in this head's mask (or -1)
    lookup = np.full((an_all.shape[0],), -1, np.int32)
    for li, g in enumerate(mask_np):
        lookup[g] = li
    local_an = jnp.asarray(lookup)[best_an]                    # [N,B]
    resp = valid & (local_an >= 0)

    gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)        # [N,B]
    gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
    la = jnp.maximum(local_an, 0)

    # --- location loss at responsible cells
    tx = gx * w - gi
    ty = gy * h - gj
    tw = jnp.log(jnp.maximum(
        gw * input_size / jnp.asarray(an_use)[la][..., 0], 1e-9))
    th = jnp.log(jnp.maximum(
        gh * input_size / jnp.asarray(an_use)[la][..., 1], 1e-9))
    scale = (2.0 - gw * gh) * gt_score                         # [N,B]

    bidx = jnp.arange(n)[:, None].repeat(b, 1)                 # [N,B]
    px_g = px[bidx, la, gj, gi]
    py_g = py[bidx, la, gj, gi]
    pw_g = pw[bidx, la, gj, gi]
    ph_g = ph_[bidx, la, gj, gi]
    loc = (_sce(px_g, tx) + _sce(py_g, ty)
           + jnp.abs(pw_g - tw) + jnp.abs(ph_g - th)) * scale
    loss_loc = jnp.where(resp, loc, 0.0).sum(1)                # [N]

    # --- class loss at responsible cells
    # reference: label_pos = 1 - s, label_neg = s, s = min(1/C, 1/40)
    smooth = min(1.0 / cn, 1.0 / 40.0) if use_label_smooth else 0.0
    onehot = jax.nn.one_hot(gt_label, cn, dtype=x.dtype)
    tcls = onehot * (1.0 - smooth) + (1.0 - onehot) * smooth
    pcls_g = pcls.transpose(0, 1, 3, 4, 2)[bidx, la, gj, gi]   # [N,B,C]
    cls = (_sce(pcls_g, tcls) * gt_score[..., None]).sum(-1)
    loss_cls = jnp.where(resp, cls, 0.0).sum(1)

    # --- objectness: build tobj by scatter; ignore high-IoU preds
    # decoded pred boxes for ignore mask
    grid_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    bx = (jax.nn.sigmoid(px) + grid_x) / w                     # [N,A,H,W]
    by = (jax.nn.sigmoid(py) + grid_y) / h
    bw = jnp.exp(jnp.clip(pw, -20, 20)) * jnp.asarray(
        an_use[:, 0])[None, :, None, None] / input_size
    bh = jnp.exp(jnp.clip(ph_, -20, 20)) * jnp.asarray(
        an_use[:, 1])[None, :, None, None] / input_size

    def iou_xywh(bx, by, bw, bh, gx, gy, gw, gh):
        # broadcast pred [N,A,H,W] x gt [N,B] -> [N,B,A,H,W]
        px1 = (bx - bw / 2)[:, None]
        py1 = (by - bh / 2)[:, None]
        px2 = (bx + bw / 2)[:, None]
        py2 = (by + bh / 2)[:, None]
        gx1 = (gx - gw / 2)[..., None, None, None]
        gy1 = (gy - gh / 2)[..., None, None, None]
        gx2 = (gx + gw / 2)[..., None, None, None]
        gy2 = (gy + gh / 2)[..., None, None, None]
        iw = jnp.maximum(jnp.minimum(px2, gx2) - jnp.maximum(px1, gx1), 0)
        ih = jnp.maximum(jnp.minimum(py2, gy2) - jnp.maximum(py1, gy1), 0)
        inter = iw * ih
        union = ((px2 - px1) * (py2 - py1)
                 + (gx2 - gx1) * (gy2 - gy1) - inter)
        return inter / jnp.maximum(union, 1e-10)

    ious = iou_xywh(bx, by, bw, bh, gx, gy, gw, gh)            # [N,B,A,H,W]
    ious = jnp.where(valid[..., None, None, None], ious, 0.0)
    best_iou = ious.max(1)                                     # [N,A,H,W]

    tobj = jnp.zeros((n, a, h, w), x.dtype)
    score_resp = jnp.where(resp, gt_score, 0.0)
    tobj = tobj.at[bidx, la, gj, gi].max(score_resp)
    ignore = (best_iou > ignore_thresh) & (tobj <= 0)
    obj_pos = jnp.where(tobj > 1e-5, _sce(pobj, 1.0) * tobj, 0.0)
    obj_neg = jnp.where((tobj <= 1e-5) & ~ignore, _sce(pobj, 0.0), 0.0)
    loss_obj = (obj_pos + obj_neg).reshape(n, -1).sum(1)

    return loss_loc + loss_cls + loss_obj


def read_file(path):
    """reference: operators/read_file_op.cc (paddle.vision.ops.read_file)
    — raw file bytes as a uint8 vector. Host-side eager op."""
    with open(path, "rb") as f:
        data = f.read()
    import numpy as _np
    return jnp.asarray(_np.frombuffer(data, _np.uint8))


def decode_jpeg(x, mode="unchanged"):
    """reference: operators/decode_jpeg_op.cu (paddle.vision.ops
    .decode_jpeg, nvjpeg-backed there) — decode a uint8 byte vector to a
    [C, H, W] uint8 image. Host-side eager op (PIL)."""
    import io as _io

    import numpy as _np
    from PIL import Image
    raw = _np.asarray(x).astype(_np.uint8).tobytes()
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)
