"""NLP-match / CTR / tree-index op family.

Reference parity: paddle/fluid/operators/ sequence_topk_avg_pooling_op,
match_matrix_tensor_op, var_conv_2d_op, tree_conv_op (math/tree2col),
pyramid_hash_op, rank_attention_op, filter_by_instag_op, tdm_child_op,
tdm_sampler_op, hash_op, sampling_id_op, similarity_focus_op,
pad_constant_like_op, random_crop_op. Dense/jittable where shapes are
static; instag filtering and tdm sampling are host-side eager like the
reference's CPU kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key


def sequence_topk_avg_pooling(x, row_lengths, col_lengths, topks,
                              channel_num: int):
    """Top-k average pooling over similarity matrices
    (sequence_topk_avg_pooling_op.h): x [batch, C, H, W] with per-row
    valid (row_len, col_len); for each k in topks, the mean of the k
    largest valid entries per channel row. Returns
    [batch, H, C * len(topks)]."""
    x = jnp.asarray(x)
    b, c, h, w = x.shape
    rl = jnp.asarray(row_lengths)
    cl = jnp.asarray(col_lengths)
    col_valid = jnp.arange(w)[None, None, None, :] < \
        cl[:, None, None, None]
    masked = jnp.where(col_valid, x, -jnp.inf)
    max_k = max(int(k) for k in topks)
    vals, _ = jax.lax.top_k(masked, min(max_k, w))      # [b, c, h, K]
    counts = jnp.minimum(cl[:, None, None, None],
                         jnp.arange(1, vals.shape[-1] + 1)[None, None,
                                                           None, :])
    csum = jnp.cumsum(jnp.where(jnp.isfinite(vals), vals, 0), axis=-1)
    outs = []
    for k in topks:
        kk = min(int(k), vals.shape[-1])
        denom = jnp.maximum(counts[..., kk - 1], 1).astype(x.dtype)
        outs.append(csum[..., kk - 1] / denom)          # [b, c, h]
    out = jnp.stack(outs, axis=-1)                      # [b, c, h, K]
    row_valid = jnp.arange(h)[None, None, :, None] < \
        rl[:, None, None, None]
    out = jnp.where(row_valid, out, 0)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(b, h, -1)


def match_matrix_tensor(x, y, w, x_lengths=None, y_lengths=None,
                        dim_t: int | None = None):
    """Semantic matching tensor (match_matrix_tensor_op.h):
    x [b, lx, dx], y [b, ly, dy], w [dx, dim_t, dy] ->
    out [b, dim_t, lx, ly] = x_i^T W_t y_j, masked past valid lengths."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    w = jnp.asarray(w)
    out = jnp.einsum("bid,dte,bje->btij", x, w, y)
    if x_lengths is not None:
        mx = jnp.arange(x.shape[1])[None, None, :, None] < \
            jnp.asarray(x_lengths)[:, None, None, None]
        out = jnp.where(mx, out, 0)
    if y_lengths is not None:
        my = jnp.arange(y.shape[1])[None, None, None, :] < \
            jnp.asarray(y_lengths)[:, None, None, None]
        out = jnp.where(my, out, 0)
    return out


def var_conv_2d(x, row_lengths, col_lengths, weight, input_channel: int,
                output_channel: int, filter_size: int, stride: int = 1):
    """Variable-size 2-D conv over per-sample valid regions
    (var_conv_2d_op.h): x [b, C, H, W] padded, weight
    [out_c, in_c, k, k]; positions outside (row_len, col_len) are zeroed
    before and after the conv."""
    from .nn_functional import conv2d
    x = jnp.asarray(x)
    b, c, h, w = x.shape
    rm = jnp.arange(h)[None, None, :, None] < \
        jnp.asarray(row_lengths)[:, None, None, None]
    cm = jnp.arange(w)[None, None, None, :] < \
        jnp.asarray(col_lengths)[:, None, None, None]
    x = jnp.where(rm & cm, x, 0)
    out = conv2d(x, jnp.asarray(weight), stride=stride,
                 padding=filter_size // 2)
    oh, ow = out.shape[2], out.shape[3]
    orl = jnp.minimum((jnp.asarray(row_lengths) + stride - 1) // stride,
                      oh)
    ocl = jnp.minimum((jnp.asarray(col_lengths) + stride - 1) // stride,
                      ow)
    rm = jnp.arange(oh)[None, None, :, None] < orl[:, None, None, None]
    cm = jnp.arange(ow)[None, None, None, :] < ocl[:, None, None, None]
    return jnp.where(rm & cm, out, 0)


def tree_conv(nodes_vector, edge_set, filter, max_depth: int = 2):  # noqa: A002
    """Tree-based convolution (tree_conv_op.h, math/tree2col): for each
    node, combine its <= max_depth-hop neighborhood with three positional
    weights (top/left/right mix, simplified to the reference's eta
    parameterization). nodes_vector [b, n, f], edge_set [b, e, 2]
    parent->child pairs (0-padded), filter [f, 3, out]."""
    nv = jnp.asarray(nodes_vector)
    es = jnp.asarray(edge_set)
    w = jnp.asarray(filter)
    b, n, f = nv.shape
    # adjacency (parent->child) as dense [b, n, n]
    src = es[..., 0]
    dst = es[..., 1]
    valid = (src != dst)  # 0-padded rows have src == dst == 0

    def adj_one(s, d, v):
        a = jnp.zeros((n, n))
        return a.at[s, d].add(jnp.where(v, 1.0, 0.0))

    adj = jax.vmap(adj_one)(src, dst, valid)
    feats = [nv]                               # depth 0: self
    reach = adj
    for _ in range(max_depth - 1):
        feats.append(jnp.einsum("bij,bjf->bif", reach, nv))
        reach = jnp.einsum("bij,bjk->bik", reach, adj)
    # three positional roles: self, children-aggregate, depth-2 aggregate
    roles = [feats[0],
             feats[1] if len(feats) > 1 else jnp.zeros_like(nv),
             feats[2] if len(feats) > 2 else jnp.zeros_like(nv)]
    stacked = jnp.stack(roles, axis=2)          # [b, n, 3, f]
    return jnp.einsum("bnrf,fro->bno", stacked, w)


_HASH_PRIME = 0x9E3779B1


def hash_ids(x, num_hash: int = 1, mod_by: int = 100000007):
    """Multiplicative hashing of int ids (hash_op.cc, xxhash there):
    x [.., 1] -> [.., num_hash] hashed ids in [0, mod_by)."""
    x = jnp.asarray(x).astype(jnp.uint32)
    outs = []
    for i in range(num_hash):
        h = (x * jnp.uint32(_HASH_PRIME) +
             jnp.uint32(i * 0x85EBCA77)) ^ (x >> 16)
        h = h * jnp.uint32(0xC2B2AE3D)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    return jnp.stack(outs, axis=-1)


def pyramid_hash(x, lengths, weight, num_emb: int, space_len: int,
                 pyramid_layer: int = 2, rand_len: int = 16):
    """Hashed n-gram embeddings (pyramid_hash_op.h): for window sizes
    2..pyramid_layer+1, hash each valid n-gram of x [b, maxlen] into
    ``space_len`` buckets of ``weight`` [space_len, rand_len] and sum the
    (reshaped) embeddings into [b, num_emb]."""
    x = jnp.asarray(x).astype(jnp.int64)
    w = jnp.asarray(weight)
    b, m = x.shape
    lens = jnp.asarray(lengths)
    total = jnp.zeros((b, num_emb), w.dtype)
    reps = num_emb // rand_len
    for win in range(2, pyramid_layer + 2):
        if m < win:
            break
        # rolling polynomial key per n-gram
        key = jnp.zeros((b, m - win + 1), jnp.uint32)
        for j in range(win):
            key = key * jnp.uint32(131) + \
                x[:, j:m - win + 1 + j].astype(jnp.uint32)
        valid = (jnp.arange(m - win + 1)[None, :] + win) <= lens[:, None]
        emb_rows = []
        for r in range(reps):
            idx = ((key * jnp.uint32(_HASH_PRIME) +
                    jnp.uint32(r)) % jnp.uint32(space_len)).astype(
                jnp.int32)
            e = w[idx]                          # [b, g, rand_len]
            emb_rows.append(jnp.where(valid[..., None], e, 0).sum(axis=1))
        total = total + jnp.concatenate(emb_rows, axis=-1)
    return total


def rank_attention(x, rank_offset, rank_param, max_rank: int,
                   max_size: int = 0):
    """Rank-aware attention for CTR (rank_attention_op.h): each instance
    selects a parameter block by its rank pair. x [n, d],
    rank_offset [n, 1 + 2*max_rank] (reference layout: col 0 = ins rank;
    cols 1,3,... = other ranks, cols 2,4,... = memory indices),
    rank_param [max_rank * max_rank * d, p]. out [n, p]."""
    x = jnp.asarray(x)
    ro = jnp.asarray(rank_offset).astype(jnp.int32)
    w = jnp.asarray(rank_param)
    n, d = x.shape
    p = w.shape[1]
    wb = w.reshape(max_rank * max_rank, d, p)
    ins_rank = ro[:, 0]

    def one(xi, rank_i, others):
        acc = jnp.zeros((p,), x.dtype)
        cnt = jnp.zeros((), x.dtype)
        for k in range(max_rank):
            other = others[2 * k]
            ok = (rank_i >= 0) & (other >= 0)
            block = jnp.clip(rank_i * max_rank + other, 0,
                             max_rank * max_rank - 1)
            acc = acc + jnp.where(ok, xi @ wb[block], 0.0)
            cnt = cnt + jnp.where(ok, 1.0, 0.0)
        return acc / jnp.maximum(cnt, 1.0)

    return jax.vmap(one)(x, ins_rank, ro[:, 1:])


def filter_by_instag(ins, ins_tags, filter_tags, is_lod: bool = True,
                     out_val_if_empty: float = 0.0):
    """Keep rows whose tag set intersects filter_tags
    (filter_by_instag_op.h), host-side eager. ins [n, d]; ins_tags a list
    of per-row tag lists. Returns (filtered rows, kept indices,
    loss_weight)."""
    ins = np.asarray(ins)
    want = set(int(t) for t in np.asarray(filter_tags).reshape(-1))
    keep = [i for i, tags in enumerate(ins_tags)
            if want & set(int(t) for t in np.asarray(tags).reshape(-1))]
    if not keep:
        out = np.full((1,) + ins.shape[1:], out_val_if_empty,
                      ins.dtype)
        return out, np.array([0]), np.zeros((1, 1), np.float32)
    idx = np.asarray(keep)
    return ins[idx], idx, np.ones((len(idx), 1), np.float32)


def tdm_child(x, tree_info, child_nums: int):
    """Look up each node's children in a TDM tree table (tdm_child_op.h):
    tree_info [n_nodes, 3 + child_nums] rows
    (item_id, layer, parent, child_0..child_k); 0 = no child.
    Returns (children [.., child_nums], leaf_mask)."""
    x = jnp.asarray(x).astype(jnp.int32)
    info = jnp.asarray(tree_info).astype(jnp.int32)
    children = info[x][..., 3:3 + child_nums]
    item_ids = info[children][..., 0]
    is_leaf = (info[children][..., 3:3 + child_nums].sum(-1) == 0) & \
        (children != 0)
    return children, jnp.where(children != 0, is_leaf, False).astype(
        jnp.int32)


def tdm_sampler(x, travel_list, layer_node_lists, neg_samples_per_layer,
                seed: int = 0, output_positive: bool = True):
    """Per-layer positive+negative sampling along TDM tree paths
    (tdm_sampler_op.h), host-side eager. x [n] leaf items;
    travel_list[item] = [node per layer]; layer_node_lists[l] = nodes of
    layer l. Returns (out [n, sum(counts)], labels same shape)."""
    rng = np.random.default_rng(seed)
    xs = np.asarray(x).reshape(-1)
    outs, labels = [], []
    for item in xs:
        row, lab = [], []
        path = travel_list[int(item)]
        for layer, neg_k in enumerate(neg_samples_per_layer):
            pos = path[layer]
            if output_positive:
                row.append(pos)
                lab.append(1)
            cands = [nd for nd in layer_node_lists[layer] if nd != pos]
            take = min(neg_k, len(cands))
            row.extend(rng.choice(cands, take, replace=False).tolist())
            lab.extend([0] * take)
        outs.append(row)
        labels.append(lab)
    return np.asarray(outs, np.int64), np.asarray(labels, np.int64)


def sampling_id(x, seed: int = 0, key=None):
    """Sample one index per row from probability rows (sampling_id_op.h).
    x [n, c] probabilities."""
    x = jnp.asarray(x)
    k = key if key is not None else (
        jax.random.PRNGKey(seed) if seed else next_key())
    return jax.random.categorical(k, jnp.log(jnp.maximum(x, 1e-20)),
                                  axis=-1)


def similarity_focus(x, axis: int, indexes):
    """Similarity-focus mask (similarity_focus_op.h): for each sample and
    each selected index along ``axis``, greedily mark one (row, col) max
    per rank, producing a 0/1 focus mask of x's shape. Host-side eager
    (data-dependent greedy selection)."""
    xv = np.asarray(x)
    n = xv.shape[0]
    out = np.zeros_like(xv, np.float32)
    for i in range(n):
        for idx in indexes:
            if axis == 1:
                plane = xv[i, idx]              # [h, w]
            elif axis == 2:
                plane = xv[i, :, idx]
            else:
                plane = xv[i, :, :, idx]
            h, w = plane.shape
            used_r = np.zeros(h, bool)
            used_c = np.zeros(w, bool)
            order = np.argsort(-plane.ravel())
            marked = 0
            for flat in order:
                r, cc = divmod(int(flat), w)
                if used_r[r] or used_c[cc]:
                    continue
                used_r[r] = used_c[cc] = True
                if axis == 1:
                    out[i, :, r, cc] = 1.0
                elif axis == 2:
                    out[i, r, :, cc] = 1.0
                else:
                    out[i, r, cc, :] = 1.0
                marked += 1
                if marked >= min(h, w):
                    break
    return out


def pad_constant_like(x, y, pad_value: float = 0.0):
    """Pad y up to x's shape with a constant (pad_constant_like_op.cc)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    pads = [(0, int(a) - int(b)) for a, b in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


def random_crop(x, shape, seed: int = 0, key=None):
    """Random spatial crop to ``shape`` (random_crop_op.h): the leading
    dims of x are kept, trailing len(shape) dims are cropped."""
    x = jnp.asarray(x)
    k = key if key is not None else (
        jax.random.PRNGKey(seed) if seed else next_key())
    nd = len(shape)
    lead = x.ndim - nd
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - int(s)
        k, sub = jax.random.split(k)
        starts.append(jax.random.randint(sub, (), 0, limit + 1))
    out = x
    for i, (st, s) in enumerate(zip(starts, shape)):
        out = jax.lax.dynamic_slice_in_dim(out, st, int(s), axis=lead + i)
    return out
