"""Extended math / linalg / manipulation ops.

Reference parity: the long tail of python/paddle/tensor/{math,linalg,
manipulation,stat}.py beyond the core families (frexp/ldexp/trapezoid-class
utilities, strided views, masked scatter, LU unpacking, pairwise
distances). Pure jax functions — safe under jit and from eager dispatch.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import jax.scipy.special as jsp
import numpy as np


# --- elementwise / numeric utilities ----------------------------------------

def frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


def ldexp(x, y):
    return jnp.ldexp(x, y.astype(jnp.int32) if hasattr(y, "astype") else y)


def renorm(x, p, axis, max_norm):
    """Clamp the p-norm of every sub-tensor along ``axis`` to max_norm."""
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def trapezoid(y, x=None, dx=None, axis=-1):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    y1 = _slice_axis(y, axis, 1, None)
    y0 = _slice_axis(y, axis, 0, -1)
    if x is not None:
        d = _slice_axis(x, axis, 1, None) - _slice_axis(x, axis, 0, -1)
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum(d * (y0 + y1) / 2.0, axis=axis)


def _slice_axis(a, axis, start, stop):
    idx = [slice(None)] * a.ndim
    idx[axis % a.ndim] = slice(start, stop)
    return a[tuple(idx)]


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


# --- combinatorics ----------------------------------------------------------

def cartesian_prod(xs):
    """List of 1-D tensors -> [prod(len), k] cartesian product."""
    grids = jnp.meshgrid(*xs, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


def combinations(x, r=2, with_replacement=False):
    """All r-combinations of a 1-D tensor's elements, as [C, r]."""
    n = x.shape[0]
    gen = itertools.combinations_with_replacement if with_replacement \
        else itertools.combinations
    idx = np.array(list(gen(range(n), r)), dtype=np.int32)
    if idx.size == 0:
        return jnp.zeros((0, r), x.dtype)
    return x[idx]


# --- indexing / views -------------------------------------------------------

def index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(value)


def masked_scatter(x, mask, value):
    """Fill True positions of ``mask`` with consecutive elements of
    ``value`` (row-major), like the reference masked_scatter."""
    m = jnp.broadcast_to(mask, x.shape)
    pos = jnp.cumsum(m.reshape(-1)) - 1
    src = value.reshape(-1)
    gathered = src[jnp.clip(pos, 0, src.shape[0] - 1)].reshape(x.shape)
    return jnp.where(m, gathered.astype(x.dtype), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """Batch of vectors -> batch of diagonal matrices (reference
    diag_embed_op)."""
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    rng = jnp.arange(x.shape[-1])
    r = rng + max(-offset, 0)
    c = rng + max(offset, 0)
    out = base.at[..., r, c].set(x)
    # move the two new axes to dim1/dim2
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for dest, src in order:
            perm.insert(dest, src)
        out = jnp.transpose(out, perm)
    return out


def unflatten(x, axis, shape):
    axis = axis % x.ndim
    return x.reshape(x.shape[:axis] + tuple(shape) + x.shape[axis + 1:])


def view_as(x, other):
    return x.reshape(other.shape)


def as_strided(x, shape, stride, offset=0):
    """Strided view via gather (XLA has no aliased strides; reference
    as_strided semantics on a contiguous buffer)."""
    flat = x.reshape(-1)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * st
    return flat[idx.reshape(-1)].reshape(tuple(shape))


# --- counting ---------------------------------------------------------------

def bincount(x, weights=None, minlength=0):
    """Length is data-dependent unless x is concrete (eager) — mirrors the
    reference's dynamic-output bincount."""
    length = int(max(int(jnp.max(x)) + 1 if x.size else 0, minlength))
    return jnp.bincount(x.reshape(-1), weights=weights, length=length)


# --- linalg tail ------------------------------------------------------------

def lu_unpack(lu_data, pivots, unpack_ludata=True, unpack_pivots=True):
    """(LU, pivots) -> (P, L, U) (reference lu_unpack_op). ``pivots`` are
    1-based sequential row swaps as returned by lu()."""
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_data[..., :, :k], -1) + \
            jnp.eye(m, k, dtype=lu_data.dtype)
        U = jnp.triu(lu_data[..., :k, :])
    if unpack_pivots:
        perm = jnp.broadcast_to(jnp.arange(m),
                                pivots.shape[:-1] + (m,))

        def swap(i, p):
            pi = pivots[..., i].astype(jnp.int32) - 1
            a = p[..., i]
            b = jnp.take_along_axis(p, pi[..., None], -1)[..., 0]
            p = jnp.put_along_axis(
                p, jnp.full(p.shape[:-1] + (1,), i), b[..., None], -1,
                inplace=False)
            p = jnp.put_along_axis(p, pi[..., None], a[..., None], -1,
                                   inplace=False)
            return p

        npiv = pivots.shape[-1]
        for i in range(npiv):
            perm = swap(i, perm)
        P = jax.nn.one_hot(perm, m, dtype=lu_data.dtype)
        P = jnp.swapaxes(P, -1, -2)
    return P, L, U


def cdist(x, y, p=2.0):
    """Pairwise p-distance between row sets: [..., M, D] x [..., N, D] ->
    [..., M, N]."""
    diff = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == float("inf"):
        return jnp.max(diff, axis=-1)
    if p == 0:
        return jnp.sum((diff != 0).astype(x.dtype), axis=-1)
    return jnp.sum(diff ** p, axis=-1) ** (1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = jnp.abs(x - y) + epsilon
    if p == float("inf"):
        return jnp.max(d, axis=-1, keepdims=keepdim)
    return jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)


# --- complex construction ---------------------------------------------------

def complex(real, imag):  # noqa: A001 - mirrors the public API name
    return jax.lax.complex(real, imag)


def polar(abs, angle):  # noqa: A002 - mirrors the public API name
    return abs * jnp.exp(1j * angle.astype(jnp.result_type(angle, 0.0j)))


# --- tensor-API tail --------------------------------------------------------

def take(x, index, mode="raise"):
    """Flattened-index gather (reference take: treats x as 1-D)."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"take mode must be raise/wrap/clip, got {mode!r}")
    flat = x.reshape(-1)
    idx = index.astype(jnp.int32) if hasattr(index, "astype") else index
    if mode == "wrap":
        idx = jnp.mod(idx, flat.shape[0])
    elif mode == "clip":
        idx = jnp.clip(idx, 0, flat.shape[0] - 1)
    else:  # 'raise' cannot raise under jit; clamp like gather semantics
        idx = jnp.clip(idx, -flat.shape[0], flat.shape[0] - 1)
    return flat[idx]


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def polygamma(x, n):
    return jsp.polygamma(n, x)


def i0(x):
    return jsp.i0(x)


def i0e(x):
    return jsp.i0e(x)


def i1(x):
    return jsp.i1(x)


def i1e(x):
    return jsp.i1e(x)


def digitize(x, bins, right=False):
    return jnp.digitize(x, bins, right=right)


def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def tensor_split(x, num_or_indices, axis=0):
    if isinstance(num_or_indices, int):
        return jnp.array_split(x, num_or_indices, axis=axis)
    return jnp.split(x, list(num_or_indices), axis=axis)


def hsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=2)


def atleast_1d(*xs):
    return jnp.atleast_1d(*xs)


def atleast_2d(*xs):
    return jnp.atleast_2d(*xs)


def atleast_3d(*xs):
    return jnp.atleast_3d(*xs)


def block_diag(xs):
    return jsl.block_diag(*xs)


def float_power(x, y):
    return jnp.float_power(x, y)


def addcmul(x, tensor1, tensor2, value=1.0):
    return x + value * tensor1 * tensor2


def addcdiv(x, tensor1, tensor2, value=1.0):
    return x + value * tensor1 / tensor2


def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


def is_complex(x):
    return bool(jnp.issubdtype(jnp.result_type(x), jnp.complexfloating))


def is_floating_point(x):
    return bool(jnp.issubdtype(jnp.result_type(x), jnp.floating))


def rank(x):
    return jnp.ndim(x)
