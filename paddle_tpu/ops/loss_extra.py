"""Extended loss / sampling-loss op family (pure functional).

Reference parity for the loss kernels under paddle/fluid/operators/:
hinge_loss_op.cc, rank_loss_op.cc, bpr_loss_op.cc, modified_huber_loss_op.cc,
huber_loss_op.cc, center_loss_op.cc, teacher_student_sigmoid_loss_op.cc,
squared_l2_distance_op.cc, squared_l2_norm_op.cc, l1_norm_op.cc,
cos_sim_op.cc, warpctc_op.cc (CTC via external warpctc lib there; native
log-space lax.scan here), nce_op.cc, hierarchical_sigmoid_op.cc,
sample_logits_op.cc, and the python-side dice/npair losses
(python/paddle/fluid/layers/nn.py). All are pure jax functions — safe under
jit/grad — with NumPy-precomputed static metadata where the reference used
host-side setup (hsigmoid code tables).
"""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from .nn_functional import _reduce, _sigmoid_ce


# --- simple pairwise / pointwise losses -------------------------------------

def hinge_loss(logits, labels):
    """L = max(0, 1 - y*x) with y in {-1, +1} (hinge_loss_op.cc)."""
    return jnp.maximum(0.0, 1.0 - labels * logits)


def huber_loss(input, label, delta=1.0, reduction="mean"):  # noqa: A002
    """Quadratic within |r|<=delta, linear outside (huber_loss_op.cc)."""
    r = jnp.abs(label - input)
    loss = jnp.where(r <= delta, 0.5 * r * r, delta * (r - 0.5 * delta))
    return _reduce(loss, reduction)


def modified_huber_loss(input, label):  # noqa: A002
    """Binary-classification modified huber; label in {0,1} is scaled to
    {-1,+1} (modified_huber_loss_op.cc)."""
    y = 2.0 * label - 1.0
    prod = y * input
    return jnp.where(prod >= -1.0,
                     jnp.square(jnp.maximum(0.0, 1.0 - prod)),
                     -4.0 * prod)


def rank_loss(label, left, right):
    """RankNet pairwise loss C = -P*o + log(1+e^o), o = left - right
    (rank_loss_op.cc)."""
    return _sigmoid_ce(left - right, label)


def margin_rank_loss(label, left, right, margin=0.1):
    """max(0, -label*(left-right) + margin) (margin_rank_loss_op.cc)."""
    return jnp.maximum(0.0, -label * (left - right) + margin)


def bpr_loss(input, label):  # noqa: A002
    """Bayesian personalized ranking: mean over j of
    -log(sigmoid(x[label] - x[j])) (bpr_loss_op.cc)."""
    x = input
    n = x.shape[-1]
    pos = jnp.take_along_axis(x, label.astype(jnp.int32).reshape(
        x.shape[:-1] + (1,)), axis=-1)
    diff = pos - x
    # reference averages over all j != label
    logsig = -jnp.log1p(jnp.exp(-diff))
    mask = jnp.ones_like(x) - jax.nn.one_hot(
        label.reshape(x.shape[:-1]), n, dtype=x.dtype)
    return -(logsig * mask).sum(-1, keepdims=True) / jnp.maximum(n - 1, 1)


def teacher_student_sigmoid_loss(x, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """CTR distillation loss (teacher_student_sigmoid_loss_op.cc):
    label encodes click z and optional teacher score z'."""
    x = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    z = jnp.where(label < 0.0,  # {-2: z=0, -1: z=1}
                  jnp.where(label < -1.5, 0.0, 1.0),
                  jnp.where(label < 1.0, 0.0, 1.0))
    has_teacher = label > -0.5
    zp = jnp.where(has_teacher, label - z, 0.0)
    loss = _sigmoid_ce(x, z) + jnp.where(
        has_teacher, _sigmoid_ce(x, zp), 0.0)
    return loss


def squared_l2_distance(x, y):
    """Per-row 0.5-free squared L2 distance: sum((x-y)^2) per sample
    (squared_l2_distance_op.cc). Returns (distance [N,1], sub)."""
    sub = x - y
    d = jnp.sum(jnp.square(sub).reshape(sub.shape[0], -1), axis=1,
                keepdims=True)
    return d, sub


def squared_l2_norm(x):
    """sum(x^2) over all elements (squared_l2_norm_op.cc)."""
    return jnp.sum(jnp.square(x))


def l1_norm(x):
    """sum(|x|) over all elements (l1_norm_op.cc)."""
    return jnp.sum(jnp.abs(x))


def cos_sim(x, y):
    """Row-wise cosine similarity with broadcastable y (cos_sim_op.cc)."""
    xf = x.reshape(x.shape[0], -1)
    yf = y.reshape(y.shape[0], -1)
    xn = jnp.sqrt(jnp.sum(jnp.square(xf), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(yf), axis=1, keepdims=True))
    num = jnp.sum(xf * yf, axis=1, keepdims=True)
    return num / jnp.maximum(xn * yn, 1e-12)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    """Dice coefficient loss (fluid/layers/nn.py dice_loss)."""
    label = jax.nn.one_hot(jnp.squeeze(label, -1).astype(jnp.int32),
                           input.shape[-1], dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inse = jnp.sum(input * label, axis=reduce_axes)
    dice_denom = (jnp.sum(input, axis=reduce_axes)
                  + jnp.sum(label, axis=reduce_axes))
    dice = (2.0 * inse + epsilon) / (dice_denom + epsilon)
    return jnp.mean(1.0 - dice)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (fluid/layers/nn.py npair_loss)."""
    labels = labels.reshape(-1, 1).astype(anchor.dtype)
    same = (labels == labels.T).astype(anchor.dtype)
    targets = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True), 1.0)
    logits = anchor @ positive.T
    logp = jax.nn.log_softmax(logits, axis=1)
    xent = jnp.mean(-jnp.sum(targets * logp, axis=1))
    reg = jnp.mean(jnp.sum(jnp.square(anchor), 1)
                   + jnp.sum(jnp.square(positive), 1)) * (l2_reg * 0.25)
    return xent + reg


def center_loss(x, label, centers, alpha=0.1, update_centers=True):
    """Center loss for deep face recognition (center_loss_op.cc).

    Returns (per-sample loss [N,1], updated centers). Center update follows
    the reference: delta for center c = sum over samples of (c - x) divided
    by (1 + count(label==c)), scaled by alpha.
    """
    label = label.reshape(-1).astype(jnp.int32)
    picked = centers[label]                      # [N, D]
    diff = picked - x
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if not update_centers:
        return loss, centers
    num_classes = centers.shape[0]
    counts = jnp.zeros((num_classes,), x.dtype).at[label].add(1.0)
    accum = jnp.zeros_like(centers).at[label].add(diff)
    new_centers = centers - alpha * accum / (1.0 + counts)[:, None]
    return loss, new_centers


# --- CTC (warpctc_op.cc equivalent, native log-space forward) ---------------

def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    TPU-native replacement for the reference's external warpctc binding
    (paddle/fluid/operators/warpctc_op.cc, cmake/external/warpctc): the
    forward alpha recursion runs as one lax.scan over time in log space —
    static shapes, batched over examples — and the gradient falls out of
    jax autodiff instead of a hand-written backward kernel.

    Args:
      log_probs: [T, N, C] log-softmax-normalized scores (time-major, as
        the reference's Logits after softmax; pass raw logits and they are
        normalized here).
      labels: [N, S] int labels padded with any value (mask from lengths).
      input_lengths: [N] valid time steps.
      label_lengths: [N] valid label counts.
      blank: blank index.
    """
    log_probs = jax.nn.log_softmax(log_probs, axis=-1)
    T, N, _C = log_probs.shape
    S = labels.shape[1]
    labels = labels.astype(jnp.int32)
    neg_inf = jnp.asarray(-1e30, log_probs.dtype)

    # extended label sequence: blank l1 blank l2 ... lS blank  (len 2S+1)
    ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(2 * S + 1)[None, :] < (
        2 * label_lengths[:, None] + 1)

    # can we skip from s-2 to s? only if ext[s] != blank and != ext[s-2]
    can_skip = jnp.zeros((N, 2 * S + 1), bool)
    if S > 1:
        skip = (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])
        can_skip = can_skip.at[:, 2:].set(skip)
    elif S == 1:
        can_skip = can_skip.at[:, 2].set(ext[:, 2] != blank)

    def emit(t_logp):  # [N, C] -> [N, 2S+1] scores of extended labels
        return jnp.take_along_axis(t_logp, ext, axis=1)

    alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
    e0 = emit(log_probs[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(
        label_lengths > 0, e0[:, 1], neg_inf))

    def step(alpha, t_logp):
        from_self = alpha
        from_prev = jnp.concatenate(
            [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        from_skip = jnp.concatenate(
            [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        from_skip = jnp.where(can_skip, from_skip, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(from_self, from_prev),
                               from_skip)
        new = merged + emit(t_logp)
        new = jnp.where(ext_valid, new, neg_inf)
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, N, 2S+1]

    # read alpha at t = input_length - 1, s in {2L, 2L-1}
    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    final = alphas[t_idx, jnp.arange(N)]          # [N, 2S+1]
    sL = 2 * label_lengths
    a_blank = jnp.take_along_axis(final, sL[:, None], axis=1)[:, 0]
    a_label = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(final, jnp.maximum(sL - 1, 0)[:, None],
                            axis=1)[:, 0],
        neg_inf)
    ll = jnp.logaddexp(a_blank, a_label)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(loss.dtype), 1.0)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(
            label_lengths.astype(loss.dtype), 1.0))
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


warpctc = ctc_loss


# --- sampled softmax family -------------------------------------------------

def _log_uniform_sample(key, num_samples, range_max):
    """Log-uniform (Zipfian) candidate sampler, matching the reference's
    LogUniformSampler (paddle/fluid/operators/math/sampler.cc)."""
    u = jax.random.uniform(key, (num_samples,))
    s = jnp.exp(u * _math.log(range_max + 1.0)) - 1.0
    return jnp.clip(s.astype(jnp.int32), 0, range_max - 1)


def _log_uniform_prob(ids, range_max):
    ids = ids.astype(jnp.float32)
    return jnp.log1p(1.0 / (ids + 1.0)) / _math.log(range_max + 1.0)


def sample_logits(logits, label, num_samples, key, uniq=True,
                  remove_accidental_hits=True):
    """Sample negative classes and gather their logits for sampled softmax
    (sample_logits_op.cc). Returns (sampled_logits [N, T+num_samples],
    sampled_label [N, T], samples [N, T+num_samples] — per-row class ids
    backing each sampled-logit column)."""
    n, _c = logits.shape
    range_max = logits.shape[1]
    label = label.astype(jnp.int32)
    num_true = label.shape[1]
    neg = _log_uniform_sample(key, num_samples, range_max)   # [num_samples]

    true_logit = jnp.take_along_axis(logits, label, axis=1)  # [N, T]
    neg_logit = logits[:, neg]                               # [N, S]

    # subtract log expected-count correction (sampled-softmax math):
    # with replacement E[count] = k*p; unique sampling E[count] = 1-(1-p)^k
    # (the reference LogUniformSampler's unique formula). Sampling itself is
    # with replacement either way (static shapes); uniq only switches the
    # bias correction.
    true_p = _log_uniform_prob(label, range_max)
    neg_p = _log_uniform_prob(neg, range_max)[None, :]

    def log_expected(p):
        if uniq:
            return jnp.log(jnp.maximum(-jnp.expm1(
                num_samples * jnp.log1p(-p)), 1e-20))
        return jnp.log(jnp.maximum(p * num_samples, 1e-20))

    true_logit = true_logit - log_expected(true_p).astype(logits.dtype)
    neg_logit = neg_logit - log_expected(neg_p).astype(logits.dtype)

    if remove_accidental_hits:
        hit = (neg[None, None, :] == label[:, :, None]).any(axis=1)
        neg_logit = jnp.where(hit, -1e20, neg_logit)

    sampled = jnp.concatenate([true_logit, neg_logit], axis=1)
    sampled_label = jnp.tile(jnp.arange(num_true)[None, :], (n, 1))
    # per-row class ids backing each sampled-logit column (the reference's
    # Samples output): true labels first, then the shared negatives
    samples = jnp.concatenate(
        [label, jnp.tile(neg[None, :], (n, 1))], axis=1)
    return sampled, sampled_label, samples


def nce(input, label, weight, bias=None, num_neg_samples=10, key=None,  # noqa: A002
        sample_weight=None):
    """Noise-contrastive estimation loss (nce_op.cc), log-uniform sampler.

    input: [N, D]; label: [N, T]; weight: [C, D]; bias: [C].
    Returns per-sample cost [N, 1].
    """
    if key is None:
        from ..core.rng import next_key
        key = next_key()
    n, _d = input.shape
    c = weight.shape[0]
    label = label.astype(jnp.int32)
    num_true = label.shape[1]
    neg = _log_uniform_sample(key, num_neg_samples, c)

    # O(N*T*D) gathered logits — never materialize the [N, C] matmul the
    # sampled estimator exists to avoid
    w_true = weight[label]                        # [N, T, D]
    true_logit = jnp.einsum("nd,ntd->nt", input, w_true)
    if bias is not None:
        true_logit = true_logit + bias[label]
    w_neg = weight[neg]                           # [S, D]
    neg_logit = jnp.einsum("nd,sd->ns", input, w_neg)
    if bias is not None:
        neg_logit = neg_logit + bias[neg][None, :]

    true_p = num_neg_samples * _log_uniform_prob(label, c)
    neg_p = num_neg_samples * _log_uniform_prob(neg, c)[None, :]

    # P(origin=model) = sigmoid(logit - log(k*P_noise))
    pos = jax.nn.log_sigmoid(true_logit - jnp.log(true_p))
    negs = jax.nn.log_sigmoid(-(neg_logit - jnp.log(neg_p)))
    cost = -(pos.sum(1) / num_true) - negs.sum(1)
    if sample_weight is not None:
        cost = cost * sample_weight.reshape(-1)
    return cost[:, None]


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _hsigmoid_simple_code(num_classes: int):
    """Precompute the reference's SimpleCode complete-binary-tree paths
    (paddle/fluid/operators/math/matrix_bit_code.h): class c maps to heap
    node c + num_classes; path bits are the node id's bits below the MSB."""
    max_len = int(_math.floor(_math.log2(max(num_classes, 2)))) + 1
    table = np.zeros((num_classes, max_len), np.int32)
    code = np.zeros((num_classes, max_len), np.float32)
    length = np.zeros((num_classes,), np.int32)
    for cls in range(num_classes):
        node = cls + num_classes
        bits = node.bit_length() - 1  # path length
        length[cls] = bits
        for j in range(bits):
            # internal node visited at depth j (root = 1)
            table[cls, j] = (node >> (bits - j)) - 1
            code[cls, j] = float((node >> (bits - 1 - j)) & 1)
    return table, code, length


def hsigmoid_loss(input, label, weight, bias=None, num_classes=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid loss (hierarchical_sigmoid_op.cc).

    Default tree = complete binary tree over num_classes (SimpleCode);
    custom trees via path_table [N, L] / path_code [N, L] with -1 padding.
    weight: [num_internal_nodes, D]; bias: [num_internal_nodes].
    Returns per-sample loss [N, 1].
    """
    label = label.reshape(-1).astype(jnp.int32)
    if path_table is None:
        table_np, code_np, len_np = _hsigmoid_simple_code(int(num_classes))
        table = jnp.asarray(table_np)[label]      # [N, L]
        code = jnp.asarray(code_np)[label]
        valid = (jnp.arange(table.shape[1])[None, :]
                 < jnp.asarray(len_np)[label][:, None])
    else:
        table = path_table.astype(jnp.int32)
        code = path_code.astype(input.dtype)
        valid = table >= 0
        table = jnp.maximum(table, 0)
    w = weight[table]                             # [N, L, D]
    z = jnp.einsum("nd,nld->nl", input, w)
    if bias is not None:
        z = z + bias[table]
    # BCE with target = code bit
    ce = jnp.where(valid, _sigmoid_ce(z, code.astype(z.dtype)), 0.0)
    return ce.sum(1, keepdims=True)


# reference op-name spellings (bce_loss_op.cc, kldiv_loss_op.cc)
def bce_loss(input, label, weight=None, reduction="mean"):  # noqa: A002
    from .nn_functional import binary_cross_entropy
    return binary_cross_entropy(input, label, weight=weight,
                                reduction=reduction)


def kldiv_loss(x, target, reduction="mean"):
    from .nn_functional import kl_div
    return kl_div(x, target, reduction=reduction)
