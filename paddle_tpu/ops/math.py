"""Elementwise / reduction / comparison math ops (pure functional).

Reference parity: python/paddle/tensor/math.py and
paddle/fluid/operators/elementwise/, reduce_ops/ kernel families. Pure jax
functions usable both inside jit and from the eager dispatch layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# --- binary elementwise -----------------------------------------------------

def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def mod(x, y):
    return jnp.mod(x, y)


remainder = mod


def pow(x, y):  # noqa: A001 - mirrors the public API name
    return jnp.power(x, y)


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def fmax(x, y):
    return jnp.fmax(x, y)


def fmin(x, y):
    return jnp.fmin(x, y)


def atan2(x, y):
    return jnp.arctan2(x, y)


def hypot(x, y):
    return jnp.hypot(x, y)


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def copysign(x, y):
    return jnp.copysign(x, y)


def nextafter(x, y):
    return jnp.nextafter(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def gcd(x, y):
    return jnp.gcd(x, y)


# --- unary elementwise ------------------------------------------------------

def abs(x):  # noqa: A001
    return jnp.abs(x)


def neg(x):
    return jnp.negative(x)


def exp(x):
    return jnp.exp(x)


def expm1(x):
    return jnp.expm1(x)


def log(x):
    return jnp.log(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def log1p(x):
    return jnp.log1p(x)


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return jax.lax.rsqrt(x)


def square(x):
    return jnp.square(x)


def reciprocal(x):
    return jnp.reciprocal(x)


def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def tan(x):
    return jnp.tan(x)


def asin(x):
    return jnp.arcsin(x)


def acos(x):
    return jnp.arccos(x)


def atan(x):
    return jnp.arctan(x)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def tanh(x):
    return jnp.tanh(x)


def asinh(x):
    return jnp.arcsinh(x)


def acosh(x):
    return jnp.arccosh(x)


def atanh(x):
    return jnp.arctanh(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def round(x):  # noqa: A001
    return jnp.round(x)


def trunc(input):  # noqa: A002 - reference name
    return jnp.trunc(input)


def frac(x):
    return x - jnp.trunc(x)


def sign(x):
    return jnp.sign(x)


def sgn(x):
    return jnp.sign(x)


def erf(x):
    return jax.scipy.special.erf(x)


def erfinv(x):
    return jax.scipy.special.erfinv(x)


def lgamma(x):
    return jax.scipy.special.gammaln(x)


def digamma(x):
    return jax.scipy.special.digamma(x)


def angle(x):
    return jnp.angle(x)


def conj(x):
    return jnp.conj(x)


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    if act is not None:
        # Route through the op registry (the same table nn layers use as
        # F[name]) so activation numerics match the registered ops — e.g.
        # gelu here is the exact erf form, not jax.nn's tanh approximation.
        from .. import dispatch
        act_fn = (dispatch.wrapped_ops.get(act)
                  or dispatch.wrapped_ops.get(act.replace("_", "")))
        if act_fn is None:
            # Fluid attr spellings not in the registry (e.g. older
            # underscore names) fall back to jax.nn / jnp.
            import jax.nn as _jnn
            act_fn = getattr(_jnn, act, getattr(jnp, act, None))
        if act_fn is None:
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                "scale(): unknown activation %r (not a registered op and "
                "not found in jax.nn or jax.numpy)" % (act,))
        out = act_fn(out)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def lerp(x, y, weight):
    return x + weight * (y - x)


def rad2deg(x):
    return jnp.rad2deg(x)


def deg2rad(x):
    return jnp.deg2rad(x)


def exponent(x):
    return jnp.frexp(x)[1]


# --- nan handling -----------------------------------------------------------

def isnan(x):
    return jnp.isnan(x)


def isinf(x):
    return jnp.isinf(x)


def isfinite(x):
    return jnp.isfinite(x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


# --- reductions -------------------------------------------------------------

def sum(x, axis=None, keepdim=False, dtype=None):  # noqa: A001
    return jnp.sum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=axis, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


# --- cumulative -------------------------------------------------------------

def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = jnp.ravel(x)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cummax(x, axis=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    vals = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    inds = jnp.argmax(
        jnp.where(x == vals, jnp.arange(x.shape[axis]).reshape(
            [-1 if i == axis % x.ndim else 1 for i in range(x.ndim)]), -1),
        axis=axis)
    return vals, inds


def cummin(x, axis=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    vals = jax.lax.associative_scan(jnp.minimum, x, axis=axis)
    inds = jnp.argmax(
        jnp.where(x == vals, jnp.arange(x.shape[axis]).reshape(
            [-1 if i == axis % x.ndim else 1 for i in range(x.ndim)]), -1),
        axis=axis)
    return vals, inds


def logcumsumexp(x, axis=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


# --- comparison -------------------------------------------------------------

def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


# --- logical / bitwise ------------------------------------------------------

def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def logical_not(x):
    return jnp.logical_not(x)


def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


def bitwise_not(x):
    return jnp.bitwise_not(x)


def left_shift(x, y):
    return jnp.left_shift(x, y)


def right_shift(x, y):
    return jnp.right_shift(x, y)


# --- matmul family (MXU path) ----------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if jnp.ndim(x) > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if jnp.ndim(y) > 1 else y
    return jnp.matmul(x, y)


def dot(x, y):
    # Reference dot_op semantics: 1-D -> scalar-per-batch inner product.
    if jnp.ndim(x) == 1:
        return jnp.sum(x * y)
    return jnp.sum(x * y, axis=-1)


def mm(input, mat2):  # noqa: A002 - reference names
    return jnp.matmul(input, mat2)


def bmm(x, y):
    return jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * jnp.matmul(x, y)


def inner(x, y):
    return jnp.inner(x, y)


def outer(x, y):
    return jnp.outer(x, y)


def kron(x, y):
    return jnp.kron(x, y)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def multiply_sum(x, y, axis=None):
    return jnp.sum(x * y, axis=axis)


def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def histogram(input, bins=100, min=0, max=0):  # noqa: A002
    if min == 0 and max == 0:
        rng = None
    else:
        rng = (min, max)
    hist, _ = jnp.histogram(input, bins=bins, range=rng)
    return hist


def add_n(inputs):
    """Sum a list of same-shaped tensors (reference: paddle.add_n,
    operators/sum_op.cc)."""
    if not isinstance(inputs, (list, tuple)):
        return jnp.asarray(inputs)
    out = jnp.asarray(inputs[0])
    for t in inputs[1:]:
        out = out + jnp.asarray(t)
    return out


def floor_mod(x, y):
    """Alias of mod (reference: paddle.floor_mod == elementwise_mod)."""
    return jnp.mod(x, y)


def broadcast_shape(x_shape, y_shape):
    """Broadcast result shape of two shapes (reference: paddle.broadcast_shape,
    tensor/math.py:2262). Pure host computation; returns a list."""
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))
