"""Collective communication API.

TPU-native equivalent of the reference's collective layer
(reference: python/paddle/distributed/collective.py:205 all_reduce etc.;
C++ kernels operators/collective/c_allreduce_op.h and friends; ring
management platform/collective_helper.h:68). The reference's ring_id
becomes a named mesh axis; inside a jitted/shard_mapped computation these
lower to XLA collectives over ICI/DCN (psum/all_gather/ppermute/
all_to_all) and XLA overlaps them with compute — no manual
calc/comm-stream sync ops needed (the reference's c_sync_*_stream ops have
no equivalent because the compiler schedules).

Outside a trace (eager, single-process SPMD) arrays are global: group-wide
reductions are identities w.r.t. the data the process already holds, and
multi-host eager transfers go through multihost_utils.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax._src import core as _jax_core

from ..tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _in_trace() -> bool:
    return not _jax_core.trace_state_clean()


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


def _rewrap(x, out):
    return Tensor(out, stop_gradient=True) if isinstance(x, Tensor) else out


def _axis(group):
    """Resolve a 'group' to a mesh axis name (reference ring_id -> axis)."""
    if group is None:
        return "dp"
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", "dp")


def all_reduce(tensor, op: str = ReduceOp.SUM, group=None,
               sync_op: bool = True):
    """In-trace: psum/pmax/pmin over the group axis. Eager single-process:
    identity (the process holds the global array)."""
    x = _unwrap(tensor)
    if _in_trace():
        axis = _axis(group)
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin,
              ReduceOp.AVG: jax.lax.pmean}.get(op)
        if fn is None:  # PROD via exp/log-free fallback
            out = jax.lax.all_gather(x, axis)
            out = jnp.prod(out, axis=0)
        else:
            out = fn(x, axis)
        return _rewrap(tensor, out)
    if isinstance(tensor, Tensor):
        return tensor
    return x


def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True,
               axis: int = 0):
    """In-trace gather along the group axis. Reference signature
    all_gather(tensor_list, tensor) appends per-rank shards to the list;
    the jax-native form returns the concatenated array."""
    if tensor is None:
        x = _unwrap(tensor_or_list)
        if _in_trace():
            out = jax.lax.all_gather(x, _axis(group), axis=axis,
                                     tiled=True)
            return _rewrap(tensor_or_list, out)
        return tensor_or_list
    # reference-style (list, tensor) call
    x = _unwrap(tensor)
    if _in_trace():
        out = jax.lax.all_gather(x, _axis(group))
        n = out.shape[0]
        tensor_or_list.extend(_rewrap(tensor, out[i]) for i in range(n))
    else:
        tensor_or_list.append(tensor)
    return tensor_or_list


def reduce_scatter(tensor, op: str = ReduceOp.SUM, group=None,
                   axis: int = 0):
    x = _unwrap(tensor)
    if _in_trace():
        out = jax.lax.psum_scatter(x, _axis(group), scatter_dimension=axis,
                                   tiled=True)
        return _rewrap(tensor, out)
    return tensor


def broadcast(tensor, src: int = 0, group=None, sync_op=True):
    x = _unwrap(tensor)
    if _in_trace():
        axis = _axis(group)
        # select src's value on every member of the group
        gathered = jax.lax.all_gather(x, axis)
        return _rewrap(tensor, gathered[src])
    return tensor


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None):
    # SPMD collectives are symmetric; reduce == all_reduce w.r.t. content
    return all_reduce(tensor, op, group)


def scatter(tensor, tensor_list=None, src: int = 0, group=None):
    if _in_trace():
        axis = _axis(group)
        idx = jax.lax.axis_index(axis)
        stacked = jnp.stack([_unwrap(t) for t in tensor_list]) \
            if tensor_list else _unwrap(tensor)
        picked = jax.lax.dynamic_index_in_dim(stacked, idx, keepdims=False)
        return _rewrap(tensor, picked)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             split_axis: int = 0, concat_axis: int = 0):
    """In-trace all_to_all (the exchange primitive behind expert and
    Ulysses sequence parallelism; reference only ships the raw op
    operators/collective/alltoall_op.cc)."""
    x = _unwrap(in_tensor_list) if not isinstance(in_tensor_list, list) \
        else jnp.concatenate([_unwrap(t) for t in in_tensor_list],
                             axis=split_axis)
    if _in_trace():
        out = jax.lax.all_to_all(x, _axis(group), split_axis=split_axis,
                                 concat_axis=concat_axis, tiled=True)
        return Tensor(out) if isinstance(in_tensor_list, Tensor) else out
    return in_tensor_list


def send(tensor, dst: int, group=None):
    """P2P along the pipeline axis via ppermute (reference send_v2)."""
    x = _unwrap(tensor)
    if _in_trace():
        axis = _axis(group or "pp")
        n = jax.lax.axis_size(axis)
        out = jax.lax.ppermute(x, axis,
                               [(i, (i + 1) % n) for i in range(n)])
        return _rewrap(tensor, out)
    return tensor


def recv(tensor, src: int, group=None):
    return send(tensor, src, group)


def p2p_shift(x, axis_name: str = "pp", shift: int = 1):
    """Shift values along a mesh axis (the pipeline hop primitive)."""
    if not _in_trace():
        return x
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(_unwrap(x), axis_name, perm)


def barrier(group=None):
    """Host-level sync point (reference barrier_op). In SPMD jit programs
    barriers are implicit in data dependencies; eager multi-host uses the
    coordination service."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def get_group(axis_name: str = "dp"):
    class _Group:
        def __init__(self, name):
            self.axis_name = name
            self.nranks = -1
    return _Group(axis_name)


# -- TP helper collectives (reference: collective.py:747-919 c_identity /
#    c_concat / c_split / mp_allreduce) -------------------------------------

def _c_identity(x, group=None):
    """Forward identity, backward all-reduce (column-parallel input)."""
    axis = _axis(group or "mp")

    @jax.custom_vjp
    def ident(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (jax.lax.psum(g, axis) if _in_trace() else g,)

    ident.defvjp(fwd, bwd)
    return ident(x)


def _mp_allreduce(x, group=None):
    """Forward all-reduce, backward identity (row-parallel output)."""
    axis = _axis(group or "mp")

    @jax.custom_vjp
    def ar(v):
        return jax.lax.psum(v, axis) if _in_trace() else v

    def fwd(v):
        return ar(v), None

    def bwd(_, g):
        return (g,)

    ar.defvjp(fwd, bwd)
    return ar(x)


def all_gather_object(obj, group=None):
    """Gather an arbitrary picklable host object from every PROCESS
    (reference: distributed/collective.py all_gather_object over gloo;
    here pickled bytes ride process_allgather through the coordination
    service). Returns the list in rank order."""
    import pickle

    if jax.process_count() <= 1:
        return [obj]
    from jax.experimental import multihost_utils
    import numpy as np
    data = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(
        np.array([data.size], np.int64)).ravel()
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[:data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(gathered[i, :int(sizes[i])].tobytes())
            for i in range(len(sizes))]
