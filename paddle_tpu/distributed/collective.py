"""Collective communication API.

TPU-native equivalent of the reference's collective layer
(reference: python/paddle/distributed/collective.py:205 all_reduce etc.;
C++ kernels operators/collective/c_allreduce_op.h and friends; ring
management platform/collective_helper.h:68). The reference's ring_id
becomes a named mesh axis; inside a jitted/shard_mapped computation these
lower to XLA collectives over ICI/DCN (psum/all_gather/ppermute/
all_to_all) and XLA overlaps them with compute — no manual
calc/comm-stream sync ops needed (the reference's c_sync_*_stream ops have
no equivalent because the compiler schedules).

Outside a trace (eager, single-process SPMD) arrays are global: group-wide
reductions are identities w.r.t. the data the process already holds, and
multi-host eager transfers go through multihost_utils.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from ..compat import axis_size as _compat_axis_size
import jax.numpy as jnp
from jax._src import core as _jax_core

from ..tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _in_trace() -> bool:
    return not _jax_core.trace_state_clean()


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


def _rewrap(x, out):
    return Tensor(out, stop_gradient=True) if isinstance(x, Tensor) else out


def _axis(group):
    """Resolve a 'group' to a mesh axis name (reference ring_id -> axis)."""
    if group is None:
        return "dp"
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", "dp")


def all_reduce(tensor, op: str = ReduceOp.SUM, group=None,
               sync_op: bool = True, use_calc_stream: bool = True):
    """In-trace: psum/pmax/pmin over the group axis. Eager single-process:
    identity (the process holds the global array)."""
    x = _unwrap(tensor)
    if not _in_trace():
        # eager host path only — a fault inside a trace would bake the
        # exception into the compiled program
        from .fault_inject import fault_point
        fault_point("collective.step")
    if _in_trace():
        axis = _axis(group)
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin,
              ReduceOp.AVG: jax.lax.pmean}.get(op)
        if fn is None:  # PROD via exp/log-free fallback
            out = jax.lax.all_gather(x, axis)
            out = jnp.prod(out, axis=0)
        else:
            out = fn(x, axis)
        return _rewrap(tensor, out)
    if isinstance(tensor, Tensor):
        return tensor
    return x


def all_gather(tensor_list, tensor=None, group=None, sync_op=True,
               use_calc_stream: bool = True, axis: int = 0):
    """In-trace gather along the group axis. Reference signature
    all_gather(tensor_list, tensor) appends per-rank shards to the list;
    the jax-native form returns the concatenated array."""
    if tensor is None:
        x = _unwrap(tensor_list)
        if _in_trace():
            out = jax.lax.all_gather(x, _axis(group), axis=axis,
                                     tiled=True)
            return _rewrap(tensor_list, out)
        return tensor_list
    # reference-style (list, tensor) call
    x = _unwrap(tensor)
    if _in_trace():
        out = jax.lax.all_gather(x, _axis(group))
        n = out.shape[0]
        tensor_list.extend(_rewrap(tensor, out[i]) for i in range(n))
    else:
        tensor_list.append(tensor)
    return tensor_list


def reduce_scatter(tensor, op: str = ReduceOp.SUM, group=None,
                   axis: int = 0):
    x = _unwrap(tensor)
    if _in_trace():
        out = jax.lax.psum_scatter(x, _axis(group), scatter_dimension=axis,
                                   tiled=True)
        return _rewrap(tensor, out)
    return tensor


def broadcast(tensor, src: int = 0, group=None, sync_op=True,
              use_calc_stream: bool = True):
    x = _unwrap(tensor)
    if _in_trace():
        axis = _axis(group)
        # select src's value on every member of the group
        gathered = jax.lax.all_gather(x, axis)
        return _rewrap(tensor, gathered[src])
    return tensor


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None,
           use_calc_stream: bool = True):
    # SPMD collectives are symmetric; reduce == all_reduce w.r.t. content
    return all_reduce(tensor, op, group)


def scatter(tensor, tensor_list=None, src: int = 0, group=None,
            use_calc_stream: bool = True):
    if _in_trace():
        axis = _axis(group)
        idx = jax.lax.axis_index(axis)
        stacked = jnp.stack([_unwrap(t) for t in tensor_list]) \
            if tensor_list else _unwrap(tensor)
        picked = jax.lax.dynamic_index_in_dim(stacked, idx, keepdims=False)
        return _rewrap(tensor, picked)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             use_calc_stream: bool = True,
             split_axis: int = 0, concat_axis: int = 0):
    """In-trace all_to_all (the exchange primitive behind expert and
    Ulysses sequence parallelism; reference only ships the raw op
    operators/collective/alltoall_op.cc)."""
    x = _unwrap(in_tensor_list) if not isinstance(in_tensor_list, list) \
        else jnp.concatenate([_unwrap(t) for t in in_tensor_list],
                             axis=split_axis)
    if _in_trace():
        out = jax.lax.all_to_all(x, _axis(group), split_axis=split_axis,
                                 concat_axis=concat_axis, tiled=True)
        return Tensor(out) if isinstance(in_tensor_list, Tensor) else out
    return in_tensor_list


def send(tensor, dst: int, group=None, use_calc_stream: bool = True):
    """P2P along the pipeline axis via ppermute (reference send_v2)."""
    x = _unwrap(tensor)
    if _in_trace():
        axis = _axis(group or "pp")
        n = _compat_axis_size(axis)
        out = jax.lax.ppermute(x, axis,
                               [(i, (i + 1) % n) for i in range(n)])
        return _rewrap(tensor, out)
    return tensor


def recv(tensor, src: int, group=None, use_calc_stream: bool = True):
    return send(tensor, src, group)


def p2p_shift(x, axis_name: str = "pp", shift: int = 1):
    """Shift values along a mesh axis (the pipeline hop primitive)."""
    if not _in_trace():
        return x
    n = _compat_axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(_unwrap(x), axis_name, perm)


def barrier(group=None):
    """Host-level sync point (reference barrier_op). In SPMD jit programs
    barriers are implicit in data dependencies; eager multi-host uses the
    coordination service."""
    from .fault_inject import fault_point
    fault_point("collective.step")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def get_group(id="dp"):  # noqa: A002 - reference param name
    """reference: paddle.distributed.get_group(id) — retrieve a group
    created by new_group; an axis name returns a fresh handle for that
    mesh axis."""
    id_or_axis = id
    if isinstance(id_or_axis, int):
        g = _custom_groups.get(id_or_axis)
        if g is None:
            raise ValueError(f"no group with id {id_or_axis}")
        return g
    return Group(id_or_axis)


# -- TP helper collectives (reference: collective.py:747-919 c_identity /
#    c_concat / c_split / mp_allreduce) -------------------------------------

def _c_identity(x, group=None):
    """Forward identity, backward all-reduce (column-parallel input)."""
    axis = _axis(group or "mp")

    @jax.custom_vjp
    def ident(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (jax.lax.psum(g, axis) if _in_trace() else g,)

    ident.defvjp(fwd, bwd)
    return ident(x)


def _mp_allreduce(x, group=None):
    """Forward all-reduce, backward identity (row-parallel output)."""
    axis = _axis(group or "mp")

    @jax.custom_vjp
    def ar(v):
        return jax.lax.psum(v, axis) if _in_trace() else v

    def fwd(v):
        return ar(v), None

    def bwd(_, g):
        return (g,)

    ar.defvjp(fwd, bwd)
    return ar(x)


def all_gather_object(obj, group=None):
    """Gather an arbitrary picklable host object from every PROCESS
    (reference: distributed/collective.py all_gather_object over gloo;
    here pickled bytes ride process_allgather through the coordination
    service). Returns the list in rank order."""
    import pickle

    if jax.process_count() <= 1:
        return [obj]
    from jax.experimental import multihost_utils
    import numpy as np
    data = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(
        np.array([data.size], np.int64)).ravel()
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[:data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(gathered[i, :int(sizes[i])].tobytes())
            for i in range(len(sizes))]


class Group:
    """Communication-group handle (reference: distributed/collective.py
    Group). On the mesh runtime a group is a named mesh axis; ranks is
    informational."""

    def __init__(self, axis_name: str = "dp", ranks=None, id: int = 0):  # noqa: A002
        self.axis_name = axis_name
        self.ranks = list(ranks) if ranks is not None else []
        self.id = id
        self.nranks = len(self.ranks) if self.ranks else -1

    def is_member(self) -> bool:
        import jax
        return not self.ranks or jax.process_index() in self.ranks

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(axis={self.axis_name!r}, ranks={self.ranks})"


_custom_groups = {}


def new_group(ranks=None, backend=None, axis_name: str = "dp") -> Group:
    """reference: paddle.distributed.new_group — a handle for a rank
    subset. Collectives inside jit resolve groups by mesh axis name; the
    returned Group carries that axis."""
    gid = len(_custom_groups) + 1
    g = Group(axis_name, ranks, gid)
    _custom_groups[gid] = g
    return g


def wait(tensor, group=None, use_calc_stream: bool = True) -> None:
    """reference: paddle.distributed.wait (stream sync op) — on XLA,
    device-side ordering is by data dependency; this blocks the host on
    the value like c_sync_calc_stream."""
    v = tensor.value if hasattr(tensor, "value") else tensor
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()


def split(x, size, operation: str = "linear", axis: int = 0,
          num_partitions: int = 1, gather_out: bool = True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: paddle.distributed.split (collective.py split) — run a
    linear/embedding with its weight sharded over the mp mesh axis.

    operation='linear': size=(in, out); axis=1 shards columns
    (ColumnParallelLinear), axis=0 shards rows (RowParallelLinear).
    operation='embedding': size=(vocab, dim), vocab-sharded.
    """
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr, name=name)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"unsupported split operation {operation!r}")
    if axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out, name=name)
    else:
        layer = RowParallelLinear(size[0], size[1],
                                  weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  name=name)
    return layer(x)


def c_identity(x, group=None):
    """Public spelling of the identity-with-allreduce-grad collective
    (reference: operators/collective/c_identity_op.cc)."""
    from ..tensor import Tensor as _T
    raw = x.value if isinstance(x, _T) else x
    out = _c_identity(raw, group=group)
    return _T(out) if isinstance(x, _T) else out


def concat(x, group=None, axis: int = -1):
    """Gather mp-sharded activations and concatenate along ``axis``
    (reference: operators/collective/c_concat_op.cc — the
    gather_output path of ColumnParallelLinear)."""
    parts: list = []
    all_gather(parts, x, group=group)
    import jax.numpy as _jnp

    from ..tensor import Tensor as _T
    raw = [p.value if isinstance(p, _T) else p for p in parts]
    out = _jnp.concatenate(raw, axis=axis)
    return _T(out) if isinstance(x, _T) else out
