"""DistributedStrategy — the single config object for all distributed /
optimization features.

Reference parity: python/paddle/distributed/fleet/base/
distributed_strategy.py:104 (protobuf-backed facade; properties amp:341,
recompute:428, sharding:740, pipeline:902, tensor_parallel:966,
hybrid_configs:1021, gradient_merge:1257, localsgd:1055, lamb/lars,
a_sync:258). Here a plain attribute bag with the same property surface;
the"meta-optimizer" program rewrites become sharding/remat choices inside
the fused train step.
"""

from __future__ import annotations

import copy
from typing import Any, Dict


_DEFAULTS: Dict[str, Any] = {
    # feature switches
    "amp": False,
    "recompute": False,
    "sharding": False,
    "pipeline": False,
    "tensor_parallel": False,
    "sep_parallel": False,
    "gradient_merge": False,
    "lamb": False,
    "lars": False,
    "localsgd": False,
    "adaptive_localsgd": False,
    "dgc": False,
    "fp16_allreduce": False,
    "a_sync": False,
    "heter_ccl_mode": False,
    "find_unused_parameters": False,
    "fuse_all_reduce_ops": True,
    "without_graph_optimization": False,
}

# Accepted-field names whose capability is deliberately absent: setting
# them True raises instead of silently no-oping (the migration contract
# must not lie). Heterogeneous PS scope is documented in COMPONENTS.md.
_NOT_SUPPORTED_FLAGS = {
    "heter_ccl_mode": "heterogeneous (CPU+accelerator mixed) collective "
                      "mode has no TPU-native equivalent here",
}

_DEFAULT_CONFIGS: Dict[str, Dict[str, Any]] = {
    "amp_configs": {
        "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0, "decr_ratio": 0.5,
        "use_dynamic_loss_scaling": True, "custom_white_list": [],
        "custom_black_list": [], "use_pure_fp16": False,
        "use_bf16": True, "use_fp16_guard": True,
    },
    "recompute_configs": {"checkpoints": [], "enable_offload": False},
    "sharding_configs": {
        "sharding_degree": 8, "stage": 1, "mp_degree": 1,
        "sharding_segment_strategy": "segment_broadcast_MB",
        "segment_broadcast_MB": 32.0, "gradient_merge_acc_step": 1,
        "optimize_offload": False,
    },
    "pipeline_configs": {
        "micro_batch_size": 1, "accumulate_steps": 1,
        "schedule_mode": "1F1B", "p2p_cache_shape": True,
    },
    "tensor_parallel_configs": {
        "tensor_parallel_degree": 1, "tensor_init_seed": -1,
    },
    "hybrid_configs": {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
    },
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "lamb_configs": {"lamb_weight_decay": 0.01,
                     "exclude_from_weight_decay": []},
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 1,
                       "send_queue_size": 16,
                       "independent_recv_thread": False,
                       "thread_pool_size": 1, "send_wait_times": 1,
                       "runtime_split_send_recv": False},
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
}


class DistributedStrategy:
    def __init__(self):
        self.__dict__["_flags"] = dict(_DEFAULTS)
        self.__dict__["_configs"] = copy.deepcopy(_DEFAULT_CONFIGS)

    def __getattr__(self, name):
        if name in self._flags:
            return self._flags[name]
        if name in self._configs:
            return self._configs[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        if name in self._flags:
            if name in _NOT_SUPPORTED_FLAGS and bool(value):
                from ..core.enforce import UnimplementedError
                raise UnimplementedError(
                    f"DistributedStrategy.{name}: "
                    f"{_NOT_SUPPORTED_FLAGS[name]}")
            self._flags[name] = bool(value)
        elif name in self._configs:
            cfg = self._configs[name]
            unknown = set(value) - set(cfg)
            cfg.update({k: v for k, v in value.items() if k in cfg})
            cfg.update({k: v for k, v in value.items() if k in unknown})
        else:
            object.__setattr__(self, name, value)

    def to_dict(self) -> Dict[str, Any]:
        return {"flags": dict(self._flags),
                "configs": copy.deepcopy(self._configs)}

    def __repr__(self):
        on = [k for k, v in self._flags.items() if v]
        return f"DistributedStrategy(enabled={on})"
