"""Eager data-parallel wrapper + recompute.

Reference parity:
- paddle.DataParallel (python/paddle/fluid/dygraph/parallel.py:380) whose
  C++ Reducer buckets grads and overlaps NCCL allreduce with backward
  (imperative/reducer.cc:624,798). On TPU the SPMD path
  (fleet.distributed_jit) makes the grad psum part of the compiled step —
  XLA fuses/overlaps it, so DataParallel is a thin eager-compat shim that
  averages grads across processes after backward when world_size > 1.
- recompute (python/paddle/distributed/fleet/utils/recompute.py:171):
  jax.checkpoint in traced mode; pass-through in eager mode (the eager
  tape stores residuals anyway).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..tensor import Tensor
from .env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self) -> None:
        """Average grads across processes (multi-host eager DDP). With one
        process this is a no-op; the perf path is fleet.distributed_jit."""
        if get_world_size() <= 1:
            return
        from jax.experimental import multihost_utils
        for p in self._layers.parameters():
            if p.grad is not None:
                g = multihost_utils.process_allgather(p.grad.value)
                p.grad.value = jnp.mean(g, axis=0)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def recompute(function: Callable, *args, use_reentrant=True, **kwargs):
    """Activation checkpointing (reference: fleet/utils/recompute.py:63
    RecomputeFunction — a PyLayer stashing RNG state and re-running forward
    in backward). Traced mode: jax.checkpoint (XLA rematerializes,
    trading FLOPs for HBM). Eager mode: direct call."""
    from jax._src import core as _jax_core

    if _jax_core.trace_state_clean():
        return function(*args, **kwargs)

    def raw_fn(*raw_args):
        wrapped = [Tensor(a) if isinstance(a, jax.Array) else a
                   for a in raw_args]
        out = function(*wrapped, **kwargs)
        return jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    raw_args = [a.value if isinstance(a, Tensor) else a for a in args]
    out = jax.checkpoint(raw_fn)(*raw_args)
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, out)
