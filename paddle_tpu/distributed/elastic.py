"""Elastic membership / fault tolerance.

Reference parity: python/paddle/distributed/fleet/elastic.py
(ElasticManager:87 — etcd-registered ranks, membership watch, launcher
restart on scale events, ELASTIC_EXIT_CODE=101 contract:25; recovery is
checkpoint-based). This environment ships no etcd, so the registry is
pluggable:

- TcpMembershipStore: a network registry served by
  ``MembershipServer`` (a tiny threaded TCP service any rank — usually
  the launcher on node 0 — can host). Cross-host with NO shared
  filesystem, the direct etcd analog.
- FileMembershipStore: shared filesystem (GCS-fuse/NFS on TPU pods).
- An etcd store can be registered when the client library is present.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

ELASTIC_EXIT_CODE = 101


class MembershipStore:
    """Abstract registry of live ranks."""

    def register(self, job_id: str, rank: int, meta: Dict) -> None:
        raise NotImplementedError

    def deregister(self, job_id: str, rank: int) -> None:
        raise NotImplementedError

    def members(self, job_id: str) -> Dict[int, Dict]:
        raise NotImplementedError

    def heartbeat(self, job_id: str, rank: int) -> None:
        raise NotImplementedError


class FileMembershipStore(MembershipStore):
    """Registry on a shared filesystem (GCS-fuse/NFS on TPU pods)."""

    def __init__(self, root: str, ttl_s: float = 30.0):
        self.root = root
        self.ttl_s = ttl_s
        os.makedirs(root, exist_ok=True)

    def _path(self, job_id: str, rank: int) -> str:
        d = os.path.join(self.root, job_id)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"rank_{rank}.json")

    def register(self, job_id: str, rank: int, meta: Dict) -> None:
        meta = dict(meta, ts=time.time(), host=socket.gethostname())
        with open(self._path(job_id, rank), "w") as f:
            json.dump(meta, f)

    def heartbeat(self, job_id: str, rank: int) -> None:
        from .fault_inject import fault_point
        fault_point("membership.heartbeat")
        p = self._path(job_id, rank)
        if os.path.exists(p):
            with open(p) as f:
                meta = json.load(f)
            meta["ts"] = time.time()
            with open(p, "w") as f:
                json.dump(meta, f)

    def deregister(self, job_id: str, rank: int) -> None:
        try:
            os.remove(self._path(job_id, rank))
        except FileNotFoundError:
            pass

    def members(self, job_id: str) -> Dict[int, Dict]:
        d = os.path.join(self.root, job_id)
        out: Dict[int, Dict] = {}
        if not os.path.isdir(d):
            return out
        now = time.time()
        for fn in os.listdir(d):
            if not fn.startswith("rank_"):
                continue
            try:
                with open(os.path.join(d, fn)) as f:
                    meta = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue
            if now - meta.get("ts", 0) <= self.ttl_s:
                out[int(fn[5:-5])] = meta
        return out


class MembershipServer:
    """Threaded TCP registry: the etcd analog for cross-host elastic
    membership (reference registers ranks in etcd, fleet/elastic.py:87).
    Line protocol, one JSON object per request/response:

        {"op": "reg", "job": j, "rank": r, "meta": {...}}
        {"op": "hb"|"dereg", "job": j, "rank": r}
        {"op": "members", "job": j} -> {"ok": true, "members": {...}}

    Liveness is server-side: entries older than ``ttl_s`` are pruned on
    read, so a killed rank disappears without deregistering."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 ttl_s: float = 30.0):
        self.ttl_s = ttl_s
        self._jobs: Dict[str, Dict[int, Dict]] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        with conn, conn.makefile("rwb") as f:
            for line in f:
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError) as e:
                    resp = {"ok": False, "error": str(e)}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()

    def _handle(self, req: Dict) -> Dict:
        op, job = req["op"], req["job"]
        with self._lock:
            ranks = self._jobs.setdefault(job, {})
            if op == "reg":
                meta = dict(req.get("meta") or {}, ts=time.time())
                ranks[int(req["rank"])] = meta
            elif op == "hb":
                r = int(req["rank"])
                now = time.time()
                entry = ranks.get(r)
                if entry is not None and \
                        now - entry.get("ts", 0) <= self.ttl_s:
                    entry["ts"] = now
                elif entry is not None:
                    # etcd lease semantics: an expired rank cannot be
                    # resurrected by a late heartbeat (a stalled zombie
                    # would mask the relaunched rank under the same
                    # key) — it must re-register.
                    ranks.pop(r, None)
            elif op == "dereg":
                ranks.pop(int(req["rank"]), None)
            elif op == "members":
                now = time.time()
                dead = [r for r, m in ranks.items()
                        if now - m.get("ts", 0) > self.ttl_s]
                for r in dead:
                    ranks.pop(r, None)
                return {"ok": True, "members": dict(ranks)}
            else:
                return {"ok": False, "error": f"unknown op {op!r}"}
        return {"ok": True}

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class TcpMembershipStore(MembershipStore):
    """Client of MembershipServer — no shared filesystem required. One
    short-lived connection per call keeps the client usable across
    fork/exec (the elastic relaunch path)."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))
        self.timeout_s = timeout_s

    def _call(self, req: Dict) -> Dict:
        with socket.create_connection(self.addr,
                                      timeout=self.timeout_s) as s, \
                s.makefile("rwb") as f:
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            line = f.readline()
        if not line:
            raise ConnectionError("membership server closed connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(
                f"membership server error: {resp.get('error')}")
        return resp

    def register(self, job_id: str, rank: int, meta: Dict) -> None:
        meta = dict(meta, host=socket.gethostname())
        self._call({"op": "reg", "job": job_id, "rank": rank,
                    "meta": meta})

    def heartbeat(self, job_id: str, rank: int) -> None:
        from .fault_inject import fault_point
        fault_point("membership.heartbeat")
        self._call({"op": "hb", "job": job_id, "rank": rank})

    def deregister(self, job_id: str, rank: int) -> None:
        try:
            self._call({"op": "dereg", "job": job_id, "rank": rank})
        except (ConnectionError, OSError):
            pass  # best effort: the TTL prunes us anyway

    def members(self, job_id: str) -> Dict[int, Dict]:
        got = self._call({"op": "members", "job": job_id})["members"]
        return {int(r): m for r, m in got.items()}


class ElasticManager:
    """Watches membership; triggers the restart callback when the member
    set changes (scale up/down or failure), mirroring ElasticManager's
    watch loop (reference: fleet/elastic.py:87)."""

    def __init__(self, job_id: str, rank: int, np: int,
                 store: MembershipStore,
                 on_change: Optional[Callable[[Dict[int, Dict]], None]]
                 = None, heartbeat_s: float = 5.0):
        self.job_id = job_id
        self.rank = rank
        self.np = np
        self.store = store
        self.on_change = on_change
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_members: Optional[List[int]] = None
        self.hb_failures = 0  # consecutive failed heartbeat rounds

    def start(self) -> None:
        self.store.register(self.job_id, self.rank, {"np": self.np})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.store.deregister(self.job_id, self.rank)

    def _loop(self) -> None:
        from .resilience import get_retry_policy
        policy = get_retry_policy("membership.heartbeat")
        while not self._stop.is_set():
            try:
                policy.call(self.store.heartbeat, self.job_id, self.rank,
                            site="membership.heartbeat")
                member_map = policy.call(
                    self.store.members, self.job_id,
                    site="membership.heartbeat")
            except Exception:  # noqa: BLE001 - a flaky store must not
                # kill the watch thread; the TTL decides liveness
                self.hb_failures += 1
                self._stop.wait(self.heartbeat_s)
                continue
            self.hb_failures = 0
            members = sorted(member_map)
            if self._last_members is None:
                self._last_members = members
            elif members != self._last_members:
                self._last_members = members
                if self.on_change:
                    # hand over the map we just fetched — a second,
                    # unretried store read here could throw and kill
                    # the watch thread
                    self.on_change(member_map)
            self._stop.wait(self.heartbeat_s)

    def healthy(self) -> bool:
        return len(self.store.members(self.job_id)) >= self.np
