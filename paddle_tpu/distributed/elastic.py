"""Elastic membership / fault tolerance.

Reference parity: python/paddle/distributed/fleet/elastic.py
(ElasticManager:87 — etcd-registered ranks, membership watch, launcher
restart on scale events, ELASTIC_EXIT_CODE=101 contract:25; recovery is
checkpoint-based). This environment ships no etcd, so the registry is
pluggable: a file-based store (shared filesystem — the common TPU-pod
setup) with the same watch/restart semantics; an etcd store can be
registered when the client library is present.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

ELASTIC_EXIT_CODE = 101


class MembershipStore:
    """Abstract registry of live ranks."""

    def register(self, job_id: str, rank: int, meta: Dict) -> None:
        raise NotImplementedError

    def deregister(self, job_id: str, rank: int) -> None:
        raise NotImplementedError

    def members(self, job_id: str) -> Dict[int, Dict]:
        raise NotImplementedError

    def heartbeat(self, job_id: str, rank: int) -> None:
        raise NotImplementedError


class FileMembershipStore(MembershipStore):
    """Registry on a shared filesystem (GCS-fuse/NFS on TPU pods)."""

    def __init__(self, root: str, ttl_s: float = 30.0):
        self.root = root
        self.ttl_s = ttl_s
        os.makedirs(root, exist_ok=True)

    def _path(self, job_id: str, rank: int) -> str:
        d = os.path.join(self.root, job_id)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"rank_{rank}.json")

    def register(self, job_id: str, rank: int, meta: Dict) -> None:
        meta = dict(meta, ts=time.time(), host=socket.gethostname())
        with open(self._path(job_id, rank), "w") as f:
            json.dump(meta, f)

    def heartbeat(self, job_id: str, rank: int) -> None:
        p = self._path(job_id, rank)
        if os.path.exists(p):
            with open(p) as f:
                meta = json.load(f)
            meta["ts"] = time.time()
            with open(p, "w") as f:
                json.dump(meta, f)

    def deregister(self, job_id: str, rank: int) -> None:
        try:
            os.remove(self._path(job_id, rank))
        except FileNotFoundError:
            pass

    def members(self, job_id: str) -> Dict[int, Dict]:
        d = os.path.join(self.root, job_id)
        out: Dict[int, Dict] = {}
        if not os.path.isdir(d):
            return out
        now = time.time()
        for fn in os.listdir(d):
            if not fn.startswith("rank_"):
                continue
            try:
                with open(os.path.join(d, fn)) as f:
                    meta = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue
            if now - meta.get("ts", 0) <= self.ttl_s:
                out[int(fn[5:-5])] = meta
        return out


class ElasticManager:
    """Watches membership; triggers the restart callback when the member
    set changes (scale up/down or failure), mirroring ElasticManager's
    watch loop (reference: fleet/elastic.py:87)."""

    def __init__(self, job_id: str, rank: int, np: int,
                 store: MembershipStore,
                 on_change: Optional[Callable[[Dict[int, Dict]], None]]
                 = None, heartbeat_s: float = 5.0):
        self.job_id = job_id
        self.rank = rank
        self.np = np
        self.store = store
        self.on_change = on_change
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_members: Optional[List[int]] = None

    def start(self) -> None:
        self.store.register(self.job_id, self.rank, {"np": self.np})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.store.deregister(self.job_id, self.rank)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.store.heartbeat(self.job_id, self.rank)
            members = sorted(self.store.members(self.job_id))
            if self._last_members is None:
                self._last_members = members
            elif members != self._last_members:
                self._last_members = members
                if self.on_change:
                    self.on_change(self.store.members(self.job_id))
            self._stop.wait(self.heartbeat_s)

    def healthy(self) -> bool:
        return len(self.store.members(self.job_id)) >= self.np
