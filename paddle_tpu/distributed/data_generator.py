"""Fleet data generators (reference:
python/paddle/distributed/fleet/data_generator/data_generator.py —
MultiSlotDataGenerator / MultiSlotStringDataGenerator).

A user subclass implements ``generate_sample(line)`` returning a
generator of (slot_name, values) lists; ``run_from_stdin`` /
``run_from_memory`` emit the slot-line text format consumed by
io.heavy_dataset.parse_slot_line ("slot:v1 v2;slot2:...").
"""

from __future__ import annotations

import sys
from typing import Iterable, List, Tuple


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    # -- user hooks -----------------------------------------------------------

    def generate_sample(self, line):
        """Override: return a generator yielding one or more samples, each
        a list of (slot_name, values) pairs."""
        raise NotImplementedError(
            "subclasses must implement generate_sample(line)")

    def generate_batch(self, samples):
        """Optional override for batch-level rewriting."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def set_batch(self, batch_size: int):
        self.batch_size_ = batch_size

    # -- drivers --------------------------------------------------------------

    def _format_sample(self, sample: List[Tuple[str, Iterable]]) -> str:
        parts = []
        for slot, values in sample:
            vals = " ".join(str(v) for v in values)
            parts.append(f"{slot}:{vals}")
        return ";".join(parts)

    def _iter_lines(self, lines):
        batch = []
        for line in lines:
            g = self.generate_sample(line)
            if g is None:
                continue
            for sample in g():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    for s in self.generate_batch(batch)():
                        yield self._format_sample(s)
                    batch = []
        if batch:
            for s in self.generate_batch(batch)():
                yield self._format_sample(s)

    def run_from_memory(self, lines=None):
        """Process an in-memory iterable; returns slot-format lines."""
        return list(self._iter_lines(lines or [None]))

    def run_from_stdin(self):
        """Reference entry point: stdin lines -> stdout slot lines."""
        for out in self._iter_lines(sys.stdin):
            sys.stdout.write(out + "\n")


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slot values (reference MultiSlotDataGenerator: emits
    '<num> v... ' per slot; here the canonical slot-line format)."""


class MultiSlotStringDataGenerator(DataGenerator):
    """String slot values passed through untouched (reference
    MultiSlotStringDataGenerator)."""
