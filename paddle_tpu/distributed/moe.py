"""Mixture-of-Experts with expert parallelism.

BEYOND-REFERENCE capability (SURVEY §2.3: the reference snapshot has only
the raw alltoall building block, operators/collective/alltoall_op.cc, and
no MoE). TPU-native design: experts carry a leading expert dim sharded
over a mesh axis (default: the "sharding" axis doubles as the expert axis,
the common ep=dp layout).

Dispatch is capacity-based (GShard/Switch): each expert processes at most
C = ceil(top_k * T / E * capacity_factor) tokens, so expert FLOPs are
O(k * T * capacity_factor) — independent of E — with overflow tokens
dropped (their output is the residual path only). The [E, C, H] expert
batch shards over the ep axis; GSPMD turns the scatter/gather dispatch
into the alltoall exchanges a manual implementation would issue. The
dense one-hot formulation (every expert runs every token, unrouted rows
zeroed) is kept as ``dispatch_mode="dense"`` — it is the parity oracle
for the capacity path and occasionally wins at tiny E*T.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import dispatch
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..tensor import Tensor

F = dispatch.wrapped_ops


def _route(tokens, gate_w, num_experts, top_k):
    """Shared router: top-k gates renormalized, plus the Switch-style
    load-balance aux loss inputs."""
    logits = tokens @ gate_w  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    combine = jnp.zeros((tokens.shape[0], num_experts), jnp.float32)
    combine = jnp.put_along_axis(combine, top_idx, top_vals, axis=-1,
                                 inplace=False)  # [T, E]
    me = jnp.mean(combine, axis=0)  # fraction routed per expert
    ce = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(me * ce)
    return top_vals, top_idx, combine, aux.astype(jnp.float32)


def _expert_ffn(xe, w_in, b_in, w_out, b_out, activation):
    """[E, C, H] -> [E, C, H] batched expert FFN (rides the MXU as E
    batched matmuls; sharded over ep by the params' pspecs)."""
    hmid = jnp.einsum("eth,ehf->etf", xe, w_in) + b_in[:, None, :]
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu}[activation]
    hmid = act(hmid)
    return jnp.einsum("etf,efh->eth", hmid, w_out) + b_out[:, None, :]


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert token capacity C (multiple of 8 for TPU lane tiling)."""
    c = int(np.ceil(top_k * num_tokens * capacity_factor / num_experts))
    c = max(c, top_k)
    return min(-(-c // 8) * 8, num_tokens)


def _moe_ffn(x, gate_w, w_in, b_in, w_out, b_out, num_experts, top_k,
             capacity_factor, activation, expert_axis=None):
    """Pure kernel, capacity dispatch: x [B, S, H] -> [B, S, H].

    GShard-style: token t's j-th choice goes to expert e at the slot
    given by a running per-expert count (choice-major priority: all
    first choices beat all second choices); slots >= C overflow and are
    dropped (output falls back to the residual path). Expert compute is
    [E, C, H] — O(k*T*capacity_factor) FLOPs total, independent of E.
    gate_w: [H, E]; w_in: [E, H, F]; w_out: [E, F, H].
    """
    b, s, h = x.shape
    tokens = x.reshape(b * s, h)
    t = tokens.shape[0]
    cap = moe_capacity(t, num_experts, top_k, capacity_factor)

    top_vals, top_idx, _, aux = _route(tokens, gate_w, num_experts, top_k)

    # choice-major flattening: [k*T] with all 1st choices first
    flat_e = top_idx.T.reshape(-1)
    flat_t = jnp.tile(jnp.arange(t), top_k)
    flat_g = top_vals.T.reshape(-1)
    # position of each (token, choice) within its expert's batch
    onehot = jax.nn.one_hot(flat_e, num_experts, dtype=jnp.int32)
    pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - 1,
                              flat_e[:, None], axis=1)[:, 0]  # [kT]
    keep = pos < cap
    safe_pos = jnp.where(keep, pos, cap - 1)

    # scatter tokens into the [E, C, H] expert batch (kept slots are
    # unique, so scatter-add == scatter; dropped rows add zero)
    xe = jnp.zeros((num_experts, cap, h), x.dtype)
    contrib = tokens[flat_t] * keep[:, None].astype(x.dtype)
    xe = xe.at[flat_e, safe_pos].add(contrib)
    if expert_axis is not None:
        # pin the expert batch to the ep axis so the scatter lowers to
        # the alltoall exchange instead of a replicated gather
        from .mp_layers import _constrain
        xe = _constrain(xe, expert_axis)

    ye = _expert_ffn(xe, w_in, b_in, w_out, b_out, activation)
    if expert_axis is not None:
        from .mp_layers import _constrain
        ye = _constrain(ye, expert_axis)

    # gather each choice's output back and combine with its gate
    yg = ye[flat_e, safe_pos]  # [kT, H]
    wgt = (flat_g * keep.astype(jnp.float32)).astype(x.dtype)
    out = jnp.zeros((t, h), x.dtype).at[flat_t].add(yg * wgt[:, None])
    return out.reshape(b, s, h).astype(x.dtype), aux


def _moe_ffn_dense(x, gate_w, w_in, b_in, w_out, b_out, num_experts,
                   top_k, activation):
    """Dense dispatch (no token dropping, O(E*T) expert FLOPs): combine
    weights are zero for unrouted experts. The parity oracle for
    _moe_ffn."""
    b, s, h = x.shape
    tokens = x.reshape(b * s, h)
    _, _, combine, aux = _route(tokens, gate_w, num_experts, top_k)
    # routed mask in, gate out: out[t] = sum_e g_te * FFN_e(x_t). (Gating
    # the INPUT would feed the nonlinear FFN g*x, and summing unmasked
    # outputs would leak every expert's bias-propagated FFN_e(0) into
    # every token once biases train away from zero.)
    mask = (combine > 0).astype(x.dtype)
    xe = jnp.einsum("te,th->eth", mask, tokens)
    out_e = _expert_ffn(xe, w_in, b_in, w_out, b_out, activation)
    out = jnp.einsum("te,eth->th", combine.astype(x.dtype), out_e)
    return out.reshape(b, s, h).astype(x.dtype), aux


class MoELayer(Layer):
    """Switch/top-k MoE FFN (expert-parallel over ``expert_axis``).

    ``dispatch_mode``: "capacity" (default — GShard scatter/gather with
    per-expert capacity, O(k*T) expert FLOPs, overflow drops) or "dense"
    (one-hot einsum oracle, O(E*T) FLOPs, no drops)."""

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25, activation: str = "gelu",
                 expert_axis: str = "sharding", aux_loss_weight: float =
                 0.01, dispatch_mode: str = "capacity"):
        super().__init__()
        assert dispatch_mode in ("capacity", "dense"), dispatch_mode
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.aux_loss_weight = aux_loss_weight
        self.dispatch_mode = dispatch_mode
        self.expert_axis = expert_axis
        self.last_aux_loss = None
        init = Normal(std=0.02)
        self.gate_weight = self.create_parameter(
            (hidden_size, num_experts), default_initializer=init)
        self.w_in = self.create_parameter(
            (num_experts, hidden_size, ffn_hidden_size),
            default_initializer=init)
        self.b_in = self.create_parameter((num_experts, ffn_hidden_size),
                                          is_bias=True)
        self.w_out = self.create_parameter(
            (num_experts, ffn_hidden_size, hidden_size),
            default_initializer=init)
        self.b_out = self.create_parameter((num_experts, hidden_size),
                                           is_bias=True)
        # expert dim sharded over the ep axis; mp shards the ffn dim
        self.w_in.pspec = P(expert_axis, None, "mp")
        self.b_in.pspec = P(expert_axis, "mp")
        self.w_out.pspec = P(expert_axis, "mp", None)
        self.b_out.pspec = P(expert_axis, None)

    def forward(self, x):
        if self.dispatch_mode == "dense":
            def kernel(xv, gw, wi, bi, wo, bo):
                return _moe_ffn_dense(
                    xv, gw, wi, bi, wo, bo, self.num_experts,
                    self.top_k, self.activation)
        else:
            def kernel(xv, gw, wi, bi, wo, bo):
                return _moe_ffn(
                    xv, gw, wi, bi, wo, bo, self.num_experts,
                    self.top_k, self.capacity_factor, self.activation,
                    self.expert_axis)
        out, aux = dispatch.call_fn(
            kernel, "moe_ffn", True,
            (x, self.gate_weight, self.w_in, self.b_in, self.w_out,
             self.b_out), {})
        self.last_aux_loss = aux
        return out

    def aux_loss(self):
        if self.last_aux_loss is None:
            return None
        return self.last_aux_loss * self.aux_loss_weight
