"""Mixture-of-Experts with expert parallelism.

BEYOND-REFERENCE capability (SURVEY §2.3: the reference snapshot has only
the raw alltoall building block, operators/collective/alltoall_op.cc, and
no MoE). TPU-native design: experts carry a leading expert dim sharded
over a mesh axis (default: the "sharding" axis doubles as the expert axis,
the common ep=dp layout); token dispatch uses dense one-hot combine
einsums, which GSPMD partitions into the same alltoall exchanges a manual
implementation would issue — and fuses them with the expert matmuls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import dispatch
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..tensor import Tensor

F = dispatch.wrapped_ops


def _moe_ffn(x, gate_w, w_in, b_in, w_out, b_out, num_experts, top_k,
             capacity_factor, activation):
    """Pure kernel: x [B, S, H] -> [B, S, H].

    Dense dispatch (no token dropping): combine weights are zero for
    unrouted experts, so capacity is implicit. gate_w: [H, E];
    w_in: [E, H, F]; w_out: [E, F, H].
    """
    b, s, h = x.shape
    tokens = x.reshape(b * s, h)
    logits = tokens @ gate_w  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    # renormalize the top-k gates
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    combine = jnp.zeros((tokens.shape[0], num_experts), jnp.float32)
    combine = jnp.put_along_axis(combine, top_idx, top_vals, axis=-1,
                                 inplace=False)  # [T, E]
    # expert compute: dispatch via einsum (GSPMD -> alltoall over ep axis)
    xe = jnp.einsum("te,th->eth", combine.astype(x.dtype), tokens)
    hmid = jnp.einsum("eth,ehf->etf", xe, w_in) + b_in[:, None, :]
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu}[activation]
    hmid = act(hmid)
    out_e = jnp.einsum("etf,efh->eth", hmid, w_out) + b_out[:, None, :]
    out = jnp.einsum("eth->th", out_e)
    # aux load-balancing loss (Switch-style)
    me = jnp.mean(combine, axis=0)  # fraction routed per expert
    ce = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(me * ce)
    return out.reshape(b, s, h).astype(x.dtype), aux.astype(jnp.float32)


class MoELayer(Layer):
    """Switch/top-k MoE FFN (expert-parallel over ``expert_axis``)."""

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25, activation: str = "gelu",
                 expert_axis: str = "sharding", aux_loss_weight: float =
                 0.01):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.aux_loss_weight = aux_loss_weight
        self.last_aux_loss = None
        init = Normal(std=0.02)
        self.gate_weight = self.create_parameter(
            (hidden_size, num_experts), default_initializer=init)
        self.w_in = self.create_parameter(
            (num_experts, hidden_size, ffn_hidden_size),
            default_initializer=init)
        self.b_in = self.create_parameter((num_experts, ffn_hidden_size),
                                          is_bias=True)
        self.w_out = self.create_parameter(
            (num_experts, ffn_hidden_size, hidden_size),
            default_initializer=init)
        self.b_out = self.create_parameter((num_experts, hidden_size),
                                           is_bias=True)
        # expert dim sharded over the ep axis; mp shards the ffn dim
        self.w_in.pspec = P(expert_axis, None, "mp")
        self.b_in.pspec = P(expert_axis, "mp")
        self.w_out.pspec = P(expert_axis, "mp", None)
        self.b_out.pspec = P(expert_axis, None)

    def forward(self, x):
        out, aux = dispatch.call_fn(
            lambda xv, gw, wi, bi, wo, bo: _moe_ffn(
                xv, gw, wi, bi, wo, bo, self.num_experts, self.top_k,
                self.capacity_factor, self.activation),
            "moe_ffn", True,
            (x, self.gate_weight, self.w_in, self.b_in, self.w_out,
             self.b_out), {})
        self.last_aux_loss = aux
        return out

    def aux_loss(self):
        if self.last_aux_loss is None:
            return None
        return self.last_aux_loss * self.aux_loss_weight
