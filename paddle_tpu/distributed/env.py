"""Distributed environment / bootstrap.

TPU-native equivalent of the reference's env-var contract + comm-id
bootstrap (reference: fleet launcher env contract PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS, launch_utils.py; TCP ncclUniqueId broadcast
platform/gen_comm_id_helper.cc:286 — replaced by jax.distributed's
coordination service). Process-level rank/world-size here is the multi-host
axis; per-process device parallelism is expressed through the mesh
(paddle_tpu.distributed.topology).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX (reference: paddle.distributed
    init_parallel_env / fleet.init). Single-process usage is a no-op."""
    global _initialized
    if _initialized:
        return
    coord = coordinator_address or os.environ.get("PT_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("PT_NUM_PROCESSES", os.environ.get(
            "PADDLE_TRAINERS_NUM", "1")))
    pid = process_id if process_id is not None else int(
        os.environ.get("PT_PROCESS_ID", os.environ.get(
            "PADDLE_TRAINER_ID", "0")))
    if coord and nproc > 1:
        # CPU backend needs an explicit cross-process collectives
        # implementation (the TPU backend rides ICI/DCN natively). gloo is
        # the reference's CPU fabric too (framework/fleet/gloo_wrapper.cc);
        # PT_CPU_COLLECTIVES=none opts out.
        impl = os.environ.get("PT_CPU_COLLECTIVES", "gloo")
        if impl and impl != "none":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  impl)
            except Exception:
                pass  # older jax without the option
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    _initialized = True


def get_rank() -> int:
    """Process index (multi-host rank)."""
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size() -> int:
    """Number of processes (hosts), not devices."""
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


class ParallelEnv:
    """Reference-compatible env facade (reference:
    fluid/dygraph/parallel.py ParallelEnv)."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return get_rank()
