"""fleet.meta_parallel compatibility namespace.

Reference parity: python/paddle/distributed/fleet/meta_parallel/ — the
import path reference hybrid-parallel code uses for TP layers
(parallel_layers/mp_layers.py), pipeline layers (pp_layers.py), and the
per-axis RNG tracker (parallel_layers/random.py). Everything re-exported
here lives in paddle_tpu.distributed.{mp_layers,pp} and core.rng.
"""

from ..core.rng import (RNGStatesTracker, get_rng_state_tracker)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .pp import LayerDesc, PipelineLayer, SharedLayerDesc

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy", "LayerDesc", "SharedLayerDesc",
    "PipelineLayer", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed",
]


def model_parallel_random_seed(seed: int = None) -> None:
    """reference: meta_parallel.parallel_layers.random.
    model_parallel_random_seed — reseed the global + per-axis streams."""
    import paddle_tpu as pt
    base = seed if seed is not None else 0
    pt.seed(base)
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", base)
    tracker.add("local_seed", base + 1024)
