"""Megatron-style tensor-parallel layers.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py (VocabParallelEmbedding:30,
ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249)
backed by c_embedding_op.cu / c_softmax_with_cross_entropy_op.cu and the
c_identity/c_split/mp_allreduce collectives.

TPU-native design: layers annotate their Parameters with PartitionSpecs
(param.pspec) and constrain activations with with_sharding_constraint. The
sharded train step (fleet.distributed_jit) feeds these to pjit; GSPMD then
inserts the exact collectives the reference hand-writes (identity fwd /
allreduce bwd for column input, allreduce fwd for row output, masked
gather + allreduce for the sharded embedding and softmax-CE).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import dispatch
from ..nn.initializer import get_initializer
from ..nn.layer import Layer
from ..tensor import Tensor
from .topology import get_hybrid_communicate_group

F = dispatch.wrapped_ops

# Canonical activation layout over the hybrid mesh: batch over dp+sharding,
# sequence over sep, hidden replicated (or mp for the parallel interior).
def _act_spec(ndim, hidden_axis=None):
    if ndim == 3:
        return (("dp", "sharding"), "sep", hidden_axis)
    if ndim == 2:
        return (("dp", "sharding"), hidden_axis)
    return tuple([("dp", "sharding")] + [None] * (ndim - 2) +
                 [hidden_axis])


import contextlib as _contextlib
import threading as _threading

# THREAD-LOCAL, not a module global: jit traces run on the calling
# thread, and one process may trace a serving-mesh engine (which
# disables these constraints) and a fleet/training step (which needs
# them) concurrently — a shared flag's save/restore would race and
# leak the wrong state into the other thread's trace.
_constraints_state = _threading.local()


def _constraints_disabled() -> bool:
    return getattr(_constraints_state, "disabled", False)


@_contextlib.contextmanager
def no_sharding_constraints():
    """Disable activation constraints (for computations running on a mesh
    other than the global hybrid mesh, e.g. the pipeline pp x dp mesh).
    Per-thread: only the calling thread's traces are affected."""
    prev = _constraints_disabled()
    _constraints_state.disabled = True
    try:
        yield
    finally:
        _constraints_state.disabled = prev


def _constrain(x, *spec):
    """Apply a sharding constraint when a mesh is active (inside pjit).

    Inside a manual-subset shard_map (the hybrid pipeline runs manual
    over "pp" with dp/mp/sharding/sep left to GSPMD), the constraint must
    carry a bare PartitionSpec resolved against the context's abstract
    mesh — a NamedSharding over the concrete mesh has all-Auto axis types
    and is rejected in the backward pass."""
    hcg = get_hybrid_communicate_group()
    from jax._src import core as _jax_core
    if hcg is None or _constraints_disabled() or \
            _jax_core.trace_state_clean():
        return x
    raw = x.value if isinstance(x, Tensor) else x
    try:
        manual = bool(jax.sharding.get_abstract_mesh().manual_axes)
    except Exception:
        manual = False
    sharding = (P(*spec) if manual
                else jax.sharding.NamedSharding(hcg.mesh, P(*spec)))
    out = jax.lax.with_sharding_constraint(raw, sharding)
    return Tensor(out, stop_gradient=getattr(x, "stop_gradient", True)) \
        if isinstance(x, Tensor) else out


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the mp axis."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        init = get_initializer("xavier_uniform") if weight_attr is None \
            else None
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=init)
        self.weight.pspec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F["embedding"](x, self.weight)
        return _constrain(out, *_act_spec(out.ndim))


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over mp; optional gather."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 gather_output: bool = True, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.weight.pspec = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.pspec = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F["linear"](x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, *_act_spec(out.ndim))
        # keep the hidden dim sharded on mp
        return _constrain(out, *_act_spec(out.ndim, "mp"))


class RowParallelLinear(Layer):
    """Linear with in_features sharded over mp; partial sums all-reduced
    by GSPMD when the output is required replicated."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 input_is_parallel: bool = False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.weight.pspec = P("mp", None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, *_act_spec(x.ndim, "mp"))
        out = F["linear"](x, self.weight, None)
        # forces the psum over mp while keeping batch/seq sharding
        out = _constrain(out, *_act_spec(out.ndim))
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits
    (reference: mp_layers.py:249 backed by
    c_softmax_with_cross_entropy_op.cu). Under GSPMD the reduction over the
    sharded vocab axis lowers to the same partial-softmax + allreduce."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F["cross_entropy"](input, label, reduction="none",
                                  ignore_index=self.ignore_index)
