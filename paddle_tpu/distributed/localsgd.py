"""LocalSGD: k local optimizer steps per replica, then parameter
averaging over the data-parallel axis.

Reference parity: meta_optimizers/localsgd_optimizer.py (LocalSGD and
AdaptiveLocalSGD — the static-graph rewrite inserting periodic
c_allreduce-based parameter averaging). TPU-native design: instead of
rewriting a program, each dp shard holds its OWN copy of the parameters
(stacked along a leading axis sharded over "dp" in a shard_map), local
steps run with zero cross-replica traffic, and a sync step does one
psum-average over the dp axis. The adaptive variant shrinks k as the
loss drops (AdaComm-style), like the reference's AdaptiveLocalSGD.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer import Layer, functional_state
from ..tensor import Tensor
from .topology import get_hybrid_communicate_group


class LocalSGDTrainStep:
    """Per-replica local training with periodic model averaging.

    Parameters and optimizer slots are stacked with a leading replica
    axis sharded over the mesh's "dp" axis, so replicas genuinely
    diverge between syncs (unlike SPMD-replicated params, which XLA
    keeps identical). ``sync()`` psum-averages params; it runs
    automatically every ``k_steps`` once ``begin_step`` is reached.
    """

    def __init__(self, model: Layer, optimizer, train_fn: Callable,
                 k_steps: int = 1, begin_step: int = 1,
                 adaptive: bool = False, hcg=None, seed: int = 0,
                 donate: bool = True):
        self.model = model
        self.optimizer = optimizer
        self.train_fn = train_fn
        self.k_steps = max(1, int(k_steps))
        self._k0 = self.k_steps
        self.begin_step = int(begin_step)
        self.adaptive = adaptive
        self.hcg = hcg or get_hybrid_communicate_group()
        if self.hcg is None:
            raise RuntimeError("call fleet.init(strategy) first")
        mesh = self.hcg.mesh
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        for ax in ("mp", "pp", "sep", "sharding"):
            if mesh.shape.get(ax, 1) > 1:
                raise ValueError(
                    f"LocalSGD is a data-parallel strategy; {ax} degree "
                    "must be 1 (reference meta-optimizer conflicts the "
                    "same way)")

        state = functional_state(model)
        dp = self.dp

        def stack(v):
            return jnp.broadcast_to(v[None], (dp,) + v.shape)

        rep = NamedSharding(mesh, P("dp"))
        self.params = jax.tree_util.tree_map(
            lambda v: jax.device_put(stack(v), rep), state["params"])
        self.buffers = jax.tree_util.tree_map(
            lambda v: jax.device_put(stack(v), rep), state["buffers"])
        opt_state = optimizer.init(state["params"])
        self.opt_state = jax.tree_util.tree_map(
            lambda v: jax.device_put(stack(jnp.asarray(v)), rep),
            opt_state)
        self._key = jax.random.key(seed)
        self._t = 0
        self._loss0: Optional[float] = None
        self._since_sync = 0
        self.donate = bool(donate)
        self._step_cache: dict = {}
        self._sync_fn = self._build_sync()

    # ------------------------------------------------------------- build

    def _build_step(self, batch_specs):
        model, optimizer, train_fn = self.model, self.optimizer, \
            self.train_fn
        mesh = self.mesh

        from .fleet import make_functional_loss
        loss_of = make_functional_loss(model, train_fn)

        def local_step(params, buffers, opt_state, key, lr, batch):
            # leading replica axis has local extent 1 inside shard_map
            p = jax.tree_util.tree_map(lambda v: v[0], params)
            b = jax.tree_util.tree_map(lambda v: v[0], buffers)
            s = jax.tree_util.tree_map(lambda v: v[0], opt_state)
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            (loss, nb), g = jax.value_and_grad(
                loss_of, has_aux=True)(p, b, key, batch)
            np_, ns = optimizer.apply_gradients(p, g, s, lr=lr)
            ex = lambda t: jax.tree_util.tree_map(lambda v: v[None], t)
            return ex(np_), ex(nb), ex(ns), loss[None]

        smapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp"), P(), P(), batch_specs),
            out_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
            check_vma=False)
        donate = (0, 1, 2) if self.donate else ()
        return jax.jit(smapped, donate_argnums=donate)

    def _build_sync(self):
        mesh = self.mesh
        dp = self.dp

        def avg(params):
            p = jax.tree_util.tree_map(lambda v: v[0], params)
            m = jax.tree_util.tree_map(
                lambda v: jax.lax.psum(v, "dp") / dp, p)
            return jax.tree_util.tree_map(lambda v: v[None], m)

        return jax.jit(shard_map(avg, mesh=mesh, in_specs=(P("dp"),),
                                 out_specs=P("dp"), check_vma=False))

    # --------------------------------------------------------------- api

    def __call__(self, batch):
        batch_raw = jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, batch,
            is_leaf=lambda t: isinstance(t, Tensor))
        # scalar/0-d leaves are replicated; arrays shard over dp
        specs = jax.tree_util.tree_map(
            lambda v: P("dp") if np.ndim(v) >= 1 else P(), batch_raw)
        batch_raw = jax.tree_util.tree_map(
            lambda v, sp: jax.device_put(
                jnp.asarray(v), NamedSharding(self.mesh, sp)),
            batch_raw, specs)
        cache_key = (jax.tree_util.tree_structure(batch_raw),
                     tuple(jax.tree_util.tree_leaves(specs)))
        step_fn = self._step_cache.get(cache_key)
        if step_fn is None:
            step_fn = self._step_cache[cache_key] = self._build_step(specs)
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        self.params, self.buffers, self.opt_state, losses = step_fn(
            self.params, self.buffers, self.opt_state, sub, lr, batch_raw)
        self._t += 1
        self._since_sync += 1
        loss = jnp.mean(losses)  # lazy: no host sync on local steps
        # Before begin_step the reference trains fully synchronously
        # (averaging every step); only afterwards does k-step local SGD
        # kick in (localsgd_optimizer.py begin_step semantics).
        if self._t < self.begin_step or self._since_sync >= self.k_steps:
            self.sync()
            if self.adaptive and self._t >= self.begin_step:
                self._adapt(float(loss))
        return loss

    def sync(self) -> None:
        """Average parameters across replicas (the periodic allreduce the
        reference inserts into the program)."""
        self.params = self._sync_fn(self.params)
        self._since_sync = 0

    def _adapt(self, loss: float) -> None:
        """AdaComm schedule: k shrinks as loss drops — sync MORE often
        late in training, when replica divergence hurts convergence
        most (reference: AdaptiveLocalSGD avg-loss heuristic)."""
        if self._loss0 is None:
            self._loss0 = max(loss, 1e-12)
            return
        ratio = max(loss, 1e-12) / self._loss0
        self.k_steps = max(1, int(math.ceil(self._k0 * math.sqrt(ratio))))

    def sync_to_model(self) -> None:
        self.sync()
        named_p = dict(self.model.named_parameters())
        for n, v in self.params.items():
            if n in named_p:
                named_p[n].value = v[0]
        named_b = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in named_b:
                named_b[n].value = v[0]
