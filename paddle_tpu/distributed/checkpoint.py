"""Distributed / async checkpointing over orbax.

Reference parity: the sharding-aware checkpoint paths
(unittests/dist_sharding_save.py; python/paddle/framework/io.py per-rank
state_dicts; hapi auto-checkpoint callback). TPU-native: orbax writes
sharded arrays directly from device (each host saves its shards),
optionally asynchronously — replacing the reference's per-rank pickles +
manual re-merge.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _get_checkpointer(use_async: bool = False):
    import orbax.checkpoint as ocp
    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def save_sharded(state: Dict[str, Any], path: str,
                 use_async: bool = False, retry=None) -> Optional[object]:
    """Save a pytree of (possibly sharded) jax arrays. Returns the async
    handle when use_async (call .wait_until_finished()). The write is
    retried per ``retry`` (default: the "checkpoint.write" site policy)
    — GCS/NFS targets throw transient OSErrors under preemption. With
    ``use_async`` only the DISPATCH is covered: the background write's
    own failure surfaces from wait_until_finished() un-retried, so
    callers needing durability should catch there and re-save (or use
    ResilientCheckpointManager, whose writes are synchronous and
    checksummed)."""
    from .fault_inject import fault_point
    from .resilience import get_retry_policy
    path = os.path.abspath(path)

    def _do():
        fault_point("checkpoint.write")
        ckptr = _get_checkpointer(use_async)
        ckptr.save(path, state, force=True)
        return ckptr

    policy = retry or get_retry_policy("checkpoint.write")
    ckptr = policy.call(_do, site="checkpoint.write")
    if use_async:
        return ckptr
    return None


def load_sharded(path: str, target: Optional[Dict[str, Any]] = None,
                 shardings: Optional[Dict[str, Any]] = None,
                 retry=None) -> Dict[str, Any]:
    """Restore a pytree; with ``target``/``shardings`` given, arrays are
    restored directly into those shardings (resharding on read — the
    capability the reference lacks and recovers via re-merge scripts).
    Retried per the "checkpoint.read" site policy."""
    from .fault_inject import fault_point
    from .resilience import get_retry_policy
    path = os.path.abspath(path)

    def _do():
        fault_point("checkpoint.read")
        ckptr = _get_checkpointer(False)
        if target is not None:
            abstract = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=getattr(v, "sharding", None)), target)
            return ckptr.restore(path, target=abstract)
        return ckptr.restore(path)

    policy = retry or get_retry_policy("checkpoint.read")
    return policy.call(_do, site="checkpoint.read")


class CheckpointManager:
    """Rolling checkpoint manager (keep-N, step-indexed, optional async)
    — the auto-checkpoint/resume loop (reference: hapi/callbacks.py
    ModelCheckpoint + fleet elastic checkpoint-based recovery)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_async: bool = True):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               enable_async_checkpointing=
                                               use_async)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Dict[str, Any]) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, step: Optional[int] = None,
                target: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import orbax.checkpoint as ocp
        step = step if step is not None else self._mgr.latest_step()
        if target is not None:
            abstract = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=getattr(v, "sharding", None)), target)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def save_train_state(step_obj, path: str, step: int,
                     manager: Optional[CheckpointManager] = None) -> None:
    """Checkpoint a TrainStep/ShardedTrainStep's full state (params,
    buffers, optimizer slots) preserving shardings."""
    state = {"params": step_obj.params, "buffers": step_obj.buffers,
             "opt_state": step_obj.opt_state}
    if manager is not None:
        manager.save(step, state)
    else:
        save_sharded(state, path)


def restore_train_state(step_obj, path: str = None,
                        manager: Optional[CheckpointManager] = None,
                        step: Optional[int] = None) -> None:
    target = {"params": step_obj.params, "buffers": step_obj.buffers,
              "opt_state": step_obj.opt_state}
    if manager is not None:
        state = manager.restore(step, target=target)
    else:
        state = load_sharded(path, target=target)
    step_obj.params = state["params"]
    step_obj.buffers = state["buffers"]
    step_obj.opt_state = state["opt_state"]
