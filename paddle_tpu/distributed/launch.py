"""Distributed launcher CLI (``python -m paddle_tpu.distributed.launch``).

Reference parity: fleetrun (python/paddle/distributed/fleet/launch.py:94
parse args, :243 launch_collective, :309 spawn+tail; env contract
PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS from launch_utils.py). TPU
version: one process per HOST (devices within a host are driven by SPMD),
env contract PT_PROCESS_ID / PT_NUM_PROCESSES / PT_COORDINATOR_ADDRESS
consumed by distributed.env.init_parallel_env -> jax.distributed
(coordination service replaces the reference's TCP ncclUniqueId
broadcast).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


class ProcInfo:
    def __init__(self, proc: subprocess.Popen, rank: int, log_path: str):
        self.proc = proc
        self.rank = rank
        self.log_path = log_path


def _build_env(rank: int, nproc: int, coordinator: str,
               base_env: Dict[str, str]) -> Dict[str, str]:
    env = dict(base_env)
    env.update({
        "PT_PROCESS_ID": str(rank),
        "PT_NUM_PROCESSES": str(nproc),
        "PT_COORDINATOR_ADDRESS": coordinator,
        # reference-compatible aliases
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nproc),
    })
    return env


def launch_procs(entry: List[str], nproc: int, coordinator: str,
                 log_dir: str = "log") -> List[ProcInfo]:
    os.makedirs(log_dir, exist_ok=True)
    procs = []
    for rank in range(nproc):
        env = _build_env(rank, nproc, coordinator, dict(os.environ))
        log_path = os.path.join(log_dir, f"workerlog.{rank}")
        log_f = open(log_path, "w")
        cmd = [sys.executable] + entry
        p = subprocess.Popen(cmd, env=env, stdout=log_f,
                             stderr=subprocess.STDOUT)
        procs.append(ProcInfo(p, rank, log_path))
    return procs


def watch_procs(procs: List[ProcInfo], poll_s: float = 1.0,
                timeout_s: Optional[float] = None) -> int:
    """Reference behavior (fleet/elastic.py:36 LauncherInterface
    _check_procs): any rank failing tears the job down; returns the exit
    code. ``timeout_s`` bounds the whole job (returns 124, like
    timeout(1))."""
    deadline = time.time() + timeout_s if timeout_s else None
    try:
        while True:
            if deadline and time.time() > deadline:
                print("job timed out; terminating", file=sys.stderr)
                terminate_procs(procs)
                return 124
            alive = 0
            for info in procs:
                ret = info.proc.poll()
                if ret is None:
                    alive += 1
                elif ret != 0:
                    print(f"rank {info.rank} FAILED with code {ret}; "
                          f"log: {info.log_path}", file=sys.stderr)
                    terminate_procs(procs)
                    return ret
            if alive == 0:
                return 0
            time.sleep(poll_s)
    except KeyboardInterrupt:
        terminate_procs(procs)
        return 130


def terminate_procs(procs: List[ProcInfo]) -> None:
    for info in procs:
        if info.proc.poll() is None:
            info.proc.terminate()
    deadline = time.time() + 10
    for info in procs:
        try:
            info.proc.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            info.proc.kill()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch multi-process (multi-host) training")
    parser.add_argument("--nproc", "--nnodes", type=int, default=1,
                        help="number of processes (hosts)")
    parser.add_argument("--coordinator", type=str,
                        default="127.0.0.1:12355",
                        help="coordination service address")
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("--elastic", action="store_true",
                        help="restart failed jobs from checkpoints")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--membership", type=str, default=None,
                        help="elastic membership registry: 'serve' hosts "
                             "a TCP MembershipServer here (node 0) and "
                             "exports PT_MEMBER_EP to workers; "
                             "'host:port' points workers at a registry "
                             "served elsewhere (the etcd analog — no "
                             "shared filesystem needed)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    member_srv = None
    prev_member_ep = os.environ.get("PT_MEMBER_EP")
    if args.membership == "serve":
        from .elastic import MembershipServer
        member_srv = MembershipServer()
        os.environ["PT_MEMBER_EP"] = f"127.0.0.1:{member_srv.port}"
        print(f"membership registry serving on port {member_srv.port}",
              file=sys.stderr)
    elif args.membership:
        os.environ["PT_MEMBER_EP"] = args.membership

    entry = [args.training_script] + args.training_script_args
    restarts = 0
    try:
        while True:
            procs = launch_procs(entry, args.nproc, args.coordinator,
                                 args.log_dir)
            code = watch_procs(procs)
            if code == 0 or not args.elastic or \
                    restarts >= args.max_restarts:
                return code
            restarts += 1
            print(f"elastic: restarting job (attempt {restarts}/"
                  f"{args.max_restarts})", file=sys.stderr)
            time.sleep(2.0)
    finally:
        if member_srv is not None:
            member_srv.close()
        if args.membership:  # don't leak a dead endpoint to later
            if prev_member_ep is None:  # in-process launch_main callers
                os.environ.pop("PT_MEMBER_EP", None)
            else:
                os.environ["PT_MEMBER_EP"] = prev_member_ep


if __name__ == "__main__":
    sys.exit(main())
