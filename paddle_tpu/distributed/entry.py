"""Sparse-table entry (feature-admission) configs.

Reference parity: python/paddle/distributed/entry_attr.py —
ProbabilityEntry / CountFilterEntry attached to sparse_embedding params,
controlling which new sparse features a PS table admits. Consumed by
distributed.ps sparse tables as an admission policy.
"""

from __future__ import annotations


class EntryAttr:
    """Base (reference: entry_attr.py EntryAttr)."""

    def _to_attr(self) -> str:
        raise NotImplementedError

    def admit(self, count: int, rng=None) -> bool:
        """Whether a feature seen ``count`` times should be admitted."""
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """Admit new features with probability p (reference:
    entry_attr.py ProbabilityEntry)."""

    def __init__(self, probability: float):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self._name = "probability_entry"
        self._probability = float(probability)

    def _to_attr(self) -> str:
        return f"{self._name}:{self._probability}"

    def admit(self, count: int, rng=None) -> bool:
        import random
        r = rng.random() if rng is not None else random.random()
        return r < self._probability


class CountFilterEntry(EntryAttr):
    """Admit features only after ``count_filter`` occurrences (reference:
    entry_attr.py CountFilterEntry)."""

    def __init__(self, count_filter: int):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._name = "count_filter_entry"
        self._count_filter = int(count_filter)

    def _to_attr(self) -> str:
        return f"{self._name}:{self._count_filter}"

    def admit(self, count: int, rng=None) -> bool:
        return count >= self._count_filter
