"""fleet.util — cross-rank utilities for dataset/PS training.

Reference parity: python/paddle/distributed/fleet/base/util_factory.py
(UtilBase: all_reduce:61, barrier:110, all_gather:151, get_file_shard:207,
print_on_rank:265). The reference runs these over gloo comm worlds; here
host-side values ride the same XLA collectives as tensors (over the dp
axis of the live mesh) or degenerate to local no-ops in single-process
runs, with the coordination service (jax.distributed) as the multi-host
control plane.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .env import get_rank, get_world_size


class UtilBase:
    """Host-value collectives + filelist sharding (reference UtilBase)."""

    # -- host collectives ----------------------------------------------------

    def all_reduce(self, input, mode: str = "sum",  # noqa: A002
                   comm_world: str = "worker"):
        """Elementwise reduce of a host value across ranks."""
        vals = self.all_gather(input, comm_world)
        arr = np.asarray(vals)
        if mode == "sum":
            return arr.sum(axis=0)
        if mode == "max":
            return arr.max(axis=0)
        if mode == "min":
            return arr.min(axis=0)
        raise ValueError(f"unknown all_reduce mode {mode!r}")

    def all_gather(self, input, comm_world: str = "worker") -> List:  # noqa: A002
        """Gather a host value from every rank (rank order)."""
        from .collective import all_gather_object
        return all_gather_object(input)

    def barrier(self, comm_world: str = "worker") -> None:
        if get_world_size() <= 1:
            return
        from .collective import barrier
        barrier()

    # -- filelist sharding ---------------------------------------------------

    def get_file_shard(self, files: Sequence[str]) -> List[str]:
        """Split a filelist across trainers with the reference's BLOCKED
        split: consecutive spans of len(files)//world, the first
        len(files)%world ranks taking one extra — deterministic,
        disjoint, covering."""
        if not isinstance(files, (list, tuple)):
            raise TypeError("files should be a list of file paths")
        trainer_id = get_rank()
        trainers = get_world_size()
        begin, end = _blocked_range(len(files), trainer_id, trainers)
        return list(files[begin:end])

    def print_on_rank(self, message: str, rank_id: int) -> None:
        if get_rank() == rank_id:
            print(message, flush=True)


def _blocked_range(n: int, rank: int, world: int):
    """Reference get_file_shard split: blocks of n//world, the first
    n%world ranks take one extra."""
    base, rem = divmod(n, max(1, world))
    if rank < rem:
        begin = rank * (base + 1)
        end = begin + base + 1
    else:
        begin = rem * (base + 1) + (rank - rem) * base
        end = begin + base
    return begin, end


_util = UtilBase()


def fleet_util() -> UtilBase:
    return _util
