"""Sequence/context parallelism: ring attention + Ulysses.

BEYOND-REFERENCE capability (SURVEY §5: the reference has no sequence
parallelism — only the raw alltoall op, operators/collective/
alltoall_op.cc). Long-context training shards the sequence axis over a
mesh axis ("sep"):

- ring_attention: K/V blocks rotate around the ring via
  lax.ppermute while each device holds its Q shard; online-softmax
  (flash-style) accumulation keeps memory O(seq/N). On TPU each hop is
  the Pallas flash kernel with an O(S_local) custom-vjp backward.
- zigzag causal schedule: the lockstep contiguous ring leaves ~2x on
  the table for causal runs (each scan step waits for whichever device
  drew a fully-visible hop). With the sequence split into 2n half-chunks
  and device i holding chunks (i, 2n-1-i), EVERY hop does exactly two
  half-chunk-pairs of work: the local hop is plain local-causal flash,
  a hop from an earlier device attends full-q x first-half-k, a hop
  from a later device attends second-half-q x full-k. ``ring_attention
  (layout="zigzag")`` implements it; ``zigzag_permutation`` gives the
  global reorder (applied once at the model boundary by models.gpt when
  seq_parallel_mode="zigzag").
- ulysses_attention: all_to_all exchanges seq-shards for head-shards so
  each device runs full-sequence attention on a head subset, then
  exchanges back (DeepSpeed-Ulysses pattern on the alltoall primitive).

Both are written for shard_map over the hybrid mesh's "sep" axis and are
used by models.gpt when sep_degree > 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from ..compat import axis_size as _compat_axis_size
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def _block_attn(q, k, v, scale, causal_mask=None):
    """One block's contribution: returns (unnormalized out, row-max,
    row-sumexp) in fp32 for online-softmax accumulation.
    q: [B,Sq,H,D], k/v: [B,Sk,H,D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal_mask is not None:
        logits = jnp.where(causal_mask, logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)  # [B,H,Sq]
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(jnp.float32), m_safe, l


def merge_attention_blocks(acc, lse_run, out_b, lse_b):
    """Fold one block's NORMALIZED attention result (out_b, lse_b) into
    the running (acc f32 normalized, lse_run): the logsumexp merge
    out = acc*e^(lse_run-lse') + out_b*e^(lse_b-lse'). A fully-masked
    block is lse_b = -inf (weight 0). Shapes: out [..., D], lse [...]."""
    lse_new = jnp.logaddexp(lse_run, lse_b)
    # guard -inf - -inf (no mass seen yet anywhere)
    w_run = jnp.where(jnp.isneginf(lse_new), 0.0,
                      jnp.exp(lse_run - lse_new))
    w_b = jnp.where(jnp.isneginf(lse_new), 0.0, jnp.exp(lse_b - lse_new))
    acc = acc * w_run[..., None] + \
        out_b.astype(jnp.float32) * w_b[..., None]
    return acc, lse_new


def _ring_case(kv_idx, idx):
    """0 = fully visible hop, 1 = diagonal (local causal), 2 = masked."""
    return jnp.where(kv_idx < idx, 0, jnp.where(kv_idx == idx, 1, 2))


def zigzag_permutation(seq_len: int, n: int):
    """(perm, inv) index arrays for the zigzag layout over ``n`` ring
    devices: ``x[:, perm]`` puts the sequence in zigzag order (device i's
    contiguous shard holds original half-chunks i and 2n-1-i);
    ``x[:, inv]`` undoes it. n=1 is the identity."""
    if seq_len % (2 * n):
        raise ValueError(f"seq_len {seq_len} must divide 2*n ({2 * n})")
    c = seq_len // (2 * n)
    parts = []
    for i in range(n):
        parts.append(np.arange(i * c, (i + 1) * c))
        j = 2 * n - 1 - i
        parts.append(np.arange(j * c, (j + 1) * c))
    perm = np.concatenate(parts)
    inv = np.argsort(perm)
    return perm, inv


def zigzag_chunk_order(n: int, inverse: bool = False):
    """Chunk-level zigzag order over 2n half-chunks (chunk i of the
    permuted layout = chunk order[i] of the original)."""
    order = []
    for i in range(n):
        order.extend((i, 2 * n - 1 - i))
    if inverse:
        order = list(np.argsort(order))
    return order


def zigzag_reorder(x, n: int, axis: int = 1, inverse: bool = False):
    """Apply the zigzag layout as SPLIT + CONCAT of 2n chunks instead of
    a gather: static slices with shard-aligned boundaries lower to
    collective-permutes under GSPMD, where a sequence-axis gather trips
    the TPU SPMD partitioner (CHECK failure in spmd_partitioner_util)
    inside partial-manual regions. n=1 is the identity."""
    if n <= 1:
        return x
    chunks = jnp.split(x, 2 * n, axis=axis)
    order = zigzag_chunk_order(n, inverse=inverse)
    return jnp.concatenate([chunks[j] for j in order], axis=axis)


def zigzag_positions(idx, n: int, s_loc: int):
    """Global sequence positions of a device's zigzag-local rows
    (traced-friendly in the device index ``idx``)."""
    c = s_loc // 2
    r = jnp.arange(c)
    return jnp.concatenate([idx * c + r, (2 * n - 1 - idx) * c + r])


def _ring_flash_forward(q, k, v, axis_name, causal, scale):
    """Returns (normalized acc f32, global lse) — the flash residuals."""
    from ..ops.pallas.flash_attention import flash_attention_lse

    n = _compat_axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, _ = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(k_cur, v_cur, kv_idx):
        def full(_):
            return flash_attention_lse(q, k_cur, v_cur, causal=False,
                                       scale=scale)

        def diag(_):
            # same global offset on both sides: local causal mask IS the
            # global one
            return flash_attention_lse(q, k_cur, v_cur, causal=True,
                                       scale=scale)

        def skip(_):
            return (jnp.zeros(q.shape, q.dtype),
                    jnp.full((b, s_loc, h), -jnp.inf, jnp.float32))

        if not causal:
            return full(None)
        return jax.lax.switch(_ring_case(kv_idx, idx),
                              [full, diag, skip], None)

    def body(carry, t):
        k_cur, v_cur, kv_idx, acc, lse_run = carry
        out_b, lse_b = hop(k_cur, v_cur, kv_idx)
        acc, lse_run = merge_attention_blocks(acc, lse_run, out_b, lse_b)
        # the final hop's rotation feeds nobody: skip its comm volume
        # (t is uniform across devices, so the cond's collectives agree)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        return (k_nxt, v_nxt, (kv_idx - 1) % n, acc, lse_run), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, s_loc, h), -jnp.inf, jnp.float32)
    (_, _, _, acc, lse_run), _ = jax.lax.scan(
        body, (k, v, idx, acc0, lse0), jnp.arange(n))
    return acc, lse_run


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring attention whose per-hop block attention is the Pallas flash
    kernel: no [S_loc, S_loc] score tensor ever materializes, and the
    custom vjp keeps backward residuals at O(S_local) — only
    (q, k, v, out, global lse) are saved; the backward RE-ROTATES K/V
    around the ring and runs the flash backward per hop with the global
    lse (plain autodiff through the forward scan would have stored every
    rotated K/V shard, O(S_global) per device, defeating the point).
    dK/dV partials travel around the ring with their shard and arrive
    home after the full rotation."""
    acc, _ = _ring_flash_forward(q, k, v, axis_name, causal, scale)
    return acc.astype(q.dtype)


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale):
    acc, lse = _ring_flash_forward(q, k, v, axis_name, causal, scale)
    out = acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, res, do):
    from ..ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                              DEFAULT_BLOCK_Q, _flash_bwd,
                                              _resolve_blocks)

    q, k, v, out, lse = res
    n = _compat_axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]
    bq, bk = _resolve_blocks(s_loc, s_loc, DEFAULT_BLOCK_Q,
                             DEFAULT_BLOCK_K)
    # bhsd layouts for the kernels; lse [B,H,S,1]
    qT = jnp.swapaxes(q, 1, 2)
    outT = jnp.swapaxes(out, 1, 2)
    doT = jnp.swapaxes(do, 1, 2)
    lseT = jnp.swapaxes(lse, 1, 2)[..., None]
    # delta is hop-invariant: compute it once, not n times in the scan
    deltaT = jnp.sum(doT.astype(jnp.float32) * outT.astype(jnp.float32),
                     axis=-1, keepdims=True)

    def hop_bwd(k_cur, v_cur, kv_idx):
        kT = jnp.swapaxes(k_cur, 1, 2)
        vT = jnp.swapaxes(v_cur, 1, 2)

        def run(is_causal):
            def f(_):
                return _flash_bwd(qT, kT, vT, outT, lseT, doT, scale,
                                  is_causal, bq, bk, delta=deltaT)
            return f

        def skip(_):
            return (jnp.zeros_like(qT), jnp.zeros_like(kT),
                    jnp.zeros_like(vT))

        if not causal:
            return run(False)(None)
        return jax.lax.switch(_ring_case(kv_idx, idx),
                              [run(False), run(True), skip], None)

    def body(carry, t):
        k_cur, v_cur, dk_t, dv_t, kv_idx, dq_acc = carry
        dq_p, dk_b, dv_b = hop_bwd(k_cur, v_cur, kv_idx)
        dq_acc = dq_acc + jnp.swapaxes(dq_p, 1, 2).astype(jnp.float32)
        dk_t = dk_t + jnp.swapaxes(dk_b, 1, 2).astype(jnp.float32)
        dv_t = dv_t + jnp.swapaxes(dv_b, 1, 2).astype(jnp.float32)
        # the dK/dV partial buffers travel WITH their K/V shard and need
        # the FULL n rotations to arrive home (device i holds shard
        # (i - t) mod n; only after the n-th hop is every shard back at
        # its owner). The K/V operands themselves are done after the
        # last hop, so their final rotation is skipped.
        dk_nxt = jax.lax.ppermute(dk_t, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_t, axis_name, perm)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, (kv_idx - 1) % n,
                dq_acc), None

    carry0 = (k, v, jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32), idx,
              jnp.zeros(q.shape, jnp.float32))
    (_, _, dk_f, dv_f, _, dq_f), _ = jax.lax.scan(body, carry0,
                                                  jnp.arange(n))
    return (dq_f.astype(q.dtype), dk_f.astype(k.dtype),
            dv_f.astype(v.dtype))


_ring_attention_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def _zigzag_ring_flash_forward(q, k, v, axis_name, scale):
    """Causal ring forward over zigzag-laid-out shards: every hop costs
    exactly two half-chunk-pairs, so the lockstep scan is balanced (the
    contiguous layout's ~2x causal wait disappears)."""
    from ..ops.pallas.flash_attention import flash_attention_lse

    n = _compat_axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, _ = q.shape
    c = s_loc // 2
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(k_cur, v_cur, kv_idx):
        def earlier(_):
            # kv from an earlier device: its first half-chunk is fully
            # visible to all local rows, its second fully masked.
            out_b, lse_b = flash_attention_lse(
                q, k_cur[:, :c], v_cur[:, :c], causal=False, scale=scale)
            return out_b, lse_b

        def local(_):
            # zigzag-local causal IS plain local causal: qa•ka and qb•kb
            # sit on the global diagonal, qb•ka is fully visible,
            # qa•kb fully masked — exactly the row>=col local mask.
            return flash_attention_lse(q, k_cur, v_cur, causal=True,
                                       scale=scale)

        def later(_):
            # kv from a later device: only local second-half rows see it
            # (both its half-chunks precede chunk 2n-1-idx).
            out_b, lse_b = flash_attention_lse(
                q[:, c:], k_cur, v_cur, causal=False, scale=scale)
            return (jnp.concatenate(
                        [jnp.zeros((b, c, h, q.shape[-1]), q.dtype),
                         out_b], axis=1),
                    jnp.concatenate(
                        [jnp.full((b, c, h), -jnp.inf, jnp.float32),
                         lse_b], axis=1))

        return jax.lax.switch(_ring_case(kv_idx, idx),
                              [earlier, local, later], None)

    def body(carry, t):
        k_cur, v_cur, kv_idx, acc, lse_run = carry
        out_b, lse_b = hop(k_cur, v_cur, kv_idx)
        acc, lse_run = merge_attention_blocks(acc, lse_run, out_b, lse_b)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        return (k_nxt, v_nxt, (kv_idx - 1) % n, acc, lse_run), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, s_loc, h), -jnp.inf, jnp.float32)
    (_, _, _, acc, lse_run), _ = jax.lax.scan(
        body, (k, v, idx, acc0, lse0), jnp.arange(n))
    return acc, lse_run


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _zigzag_ring_attention_flash(q, k, v, axis_name, scale):
    """Balanced causal ring attention (zigzag layout) on the Pallas
    flash kernel; same O(S_local) residual contract as
    _ring_attention_flash."""
    acc, _ = _zigzag_ring_flash_forward(q, k, v, axis_name, scale)
    return acc.astype(q.dtype)


def _zigzag_flash_vjp_fwd(q, k, v, axis_name, scale):
    acc, lse = _zigzag_ring_flash_forward(q, k, v, axis_name, scale)
    out = acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _zigzag_flash_vjp_bwd(axis_name, scale, res, do):
    from ..ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                              DEFAULT_BLOCK_Q, _flash_bwd,
                                              _resolve_blocks)

    q, k, v, out, lse = res
    n = _compat_axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[1]
    c = s_loc // 2
    perm = [(i, (i + 1) % n) for i in range(n)]
    # bhsd layouts; lse [B,H,S,1]
    qT = jnp.swapaxes(q, 1, 2)
    outT = jnp.swapaxes(out, 1, 2)
    doT = jnp.swapaxes(do, 1, 2)
    lseT = jnp.swapaxes(lse, 1, 2)[..., None]
    deltaT = jnp.sum(doT.astype(jnp.float32) * outT.astype(jnp.float32),
                     axis=-1, keepdims=True)

    def hop_bwd(k_cur, v_cur, kv_idx):
        kT = jnp.swapaxes(k_cur, 1, 2)
        vT = jnp.swapaxes(v_cur, 1, 2)

        def earlier(_):
            bq, bk = _resolve_blocks(s_loc, c, DEFAULT_BLOCK_Q,
                                     DEFAULT_BLOCK_K)
            dq_p, dk_h, dv_h = _flash_bwd(
                qT, kT[:, :, :c], vT[:, :, :c], outT, lseT, doT, scale,
                False, bq, bk, delta=deltaT)
            return (dq_p,
                    jnp.concatenate([dk_h, jnp.zeros_like(dk_h)], axis=2),
                    jnp.concatenate([dv_h, jnp.zeros_like(dv_h)], axis=2))

        def local(_):
            bq, bk = _resolve_blocks(s_loc, s_loc, DEFAULT_BLOCK_Q,
                                     DEFAULT_BLOCK_K)
            return _flash_bwd(qT, kT, vT, outT, lseT, doT, scale, True,
                              bq, bk, delta=deltaT)

        def later(_):
            bq, bk = _resolve_blocks(c, s_loc, DEFAULT_BLOCK_Q,
                                     DEFAULT_BLOCK_K)
            dq_h, dk_b, dv_b = _flash_bwd(
                qT[:, :, c:], kT, vT, outT[:, :, c:], lseT[:, :, c:],
                doT[:, :, c:], scale, False, bq, bk,
                delta=deltaT[:, :, c:])
            dq_p = jnp.concatenate([jnp.zeros_like(dq_h), dq_h], axis=2)
            return dq_p, dk_b, dv_b

        return jax.lax.switch(_ring_case(kv_idx, idx),
                              [earlier, local, later], None)

    def body(carry, t):
        k_cur, v_cur, dk_t, dv_t, kv_idx, dq_acc = carry
        dq_p, dk_b, dv_b = hop_bwd(k_cur, v_cur, kv_idx)
        dq_acc = dq_acc + jnp.swapaxes(dq_p, 1, 2).astype(jnp.float32)
        dk_t = dk_t + jnp.swapaxes(dk_b, 1, 2).astype(jnp.float32)
        dv_t = dv_t + jnp.swapaxes(dv_b, 1, 2).astype(jnp.float32)
        dk_nxt = jax.lax.ppermute(dk_t, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_t, axis_name, perm)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, (kv_idx - 1) % n,
                dq_acc), None

    carry0 = (k, v, jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32), idx,
              jnp.zeros(q.shape, jnp.float32))
    (_, _, dk_f, dv_f, _, dq_f), _ = jax.lax.scan(body, carry0,
                                                  jnp.arange(n))
    return (dq_f.astype(q.dtype), dk_f.astype(k.dtype),
            dv_f.astype(v.dtype))


_zigzag_ring_attention_flash.defvjp(_zigzag_flash_vjp_fwd,
                                    _zigzag_flash_vjp_bwd)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None,
                   layout: str = "contiguous"):
    """Blockwise ring attention inside shard_map.

    q,k,v: [B, S_local, H, D] — the local sequence shard. Rotates K/V
    around ``axis_name`` with ppermute; one hop per step overlaps with the
    block matmuls (XLA schedules the permute concurrently). On TPU each
    hop runs the Pallas flash kernel with a logsumexp block merge
    (``use_flash=None`` auto-detects; the jnp online-softmax path remains
    for CPU/unsupported shapes).

    ``layout="zigzag"`` (causal only): shards are in the zigzag order of
    ``zigzag_permutation`` — balanced causal schedule, every hop does
    equal work.
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    zigzag = layout == "zigzag" and causal
    if use_flash is None:
        from ..ops.pallas.flash_attention import flash_attention_supported
        use_flash = flash_attention_supported(q.shape, k.shape)
        if zigzag and use_flash:
            # zigzag hops dispatch HALF-chunk kernels (q x k[:c] etc.):
            # the half length must itself block-align or the jnp path
            # takes over (e.g. S_local=384: 384 is a 128-multiple but
            # 192 is not)
            c = q.shape[1] // 2
            half = (q.shape[0], c, *q.shape[2:])
            use_flash = (q.shape[1] % 2 == 0 and
                         flash_attention_supported(half, half))
    if use_flash:
        scale_f = float(scale if scale is not None
                        else 1.0 / np.sqrt(q.shape[-1]))
        if zigzag:
            return _zigzag_ring_attention_flash(q, k, v, axis_name,
                                                scale_f)
        return _ring_attention_flash(q, k, v, axis_name, causal, scale_f)
    n = _compat_axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if zigzag:
        q_pos = zigzag_positions(idx, n, s_loc)
        pos_of = lambda kv_index: zigzag_positions(kv_index, n, s_loc)  # noqa: E731
    else:
        q_pos = idx * s_loc + jnp.arange(s_loc)  # global positions
        pos_of = lambda kv_index: kv_index * s_loc + jnp.arange(s_loc)  # noqa: E731

    def causal_mask_for(kv_index):
        k_pos = pos_of(kv_index)
        return (q_pos[:, None] >= k_pos[None, :])[None, None]  # [1,1,Sq,Sk]

    def body(carry, t):
        k_cur, v_cur, kv_idx, acc, m_run, l_run = carry
        mask = causal_mask_for(kv_idx) if causal else None
        out_b, m_b, l_b = _block_attn(q, k_cur, v_cur, scale, mask)
        # online softmax merge
        m_new = jnp.maximum(m_run, m_b)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = l_run * alpha + l_b * beta
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + \
            out_b * beta.transpose(0, 2, 1)[..., None]
        # rotate kv to the next device (the final hop's rotation feeds
        # nobody; t is uniform so the cond's collectives agree)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: (jax.lax.ppermute(kv[0], axis_name, perm),
                        jax.lax.ppermute(kv[1], axis_name, perm)),
            lambda kv: kv, (k_cur, v_cur))
        kv_nxt = (kv_idx - 1) % n
        return (k_nxt, v_nxt, kv_nxt, acc, m_new, l_new), None

    acc0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    carry0 = (k, v, idx, acc0, m0, l0)
    (kf, vf, _, acc, m_run, l_run), _ = jax.lax.scan(
        body, carry0, jnp.arange(n))
    denom = jnp.maximum(l_run, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sep",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn=None):
    """Ulysses: alltoall seq<->head re-shard inside shard_map.

    q,k,v: [B, S_local, H, D] with H divisible by the axis size. After the
    exchange each device holds [B, S_full, H/N, D] and runs ordinary
    (flash) attention, then exchanges back.
    """
    n = _compat_axis_size(axis_name)

    def seq_to_head(x):
        # [B, S/N, H, D] -> [B, S, H/N, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if attn_fn is None:
        from ..ops.nn_functional import scaled_dot_product_attention
        out = scaled_dot_product_attention(qf, kf, vf, is_causal=causal,
                                           scale=scale, dropout_p=0.0)
    else:
        out = attn_fn(qf, kf, vf)
    return head_to_seq(out)


def ring_schedule_work(n: int, layout: str = "contiguous"):
    """Analytic causal-ring work profile: work[t][i] = half-chunk-pair
    units device i computes at hop t (full shard-pair = 4 units, local
    causal = 2, masked = 0; zigzag hops = 2 by construction). The
    lockstep scan's step time is max over i per hop; summing the maxes
    gives the schedule's critical path — the measurement behind the
    contiguous layout's ~2x causal imbalance and the zigzag fix.
    Mirrors the hop case structure of ring_attention exactly."""
    work = []
    for t in range(n):
        row = []
        for i in range(n):
            kv = (i - t) % n
            if layout == "zigzag":
                row.append(2)
            elif kv < i:
                row.append(4)
            elif kv == i:
                row.append(2)
            else:
                row.append(0)
        work.append(row)
    return work


def _axis_bound(axis_name: str) -> bool:
    try:
        _compat_axis_size(axis_name)
        return True
    except NameError:
        return False


def sequence_parallel_attention(q, k, v, mode: str = "ring",
                                axis_name: str = "sep",
                                causal: bool = False):
    """Three calling contexts, one entry point:

    - inside shard_map with ``axis_name`` bound: run the sharded
      algorithm directly (the op-level usage);
    - under jit with a live hybrid mesh whose sep degree > 1: enter a
      shard_map region here, sharding batch over (dp, sharding) and
      sequence over sep — this is what the model-level
      ``seq_parallel_mode`` config reaches through GSPMD-jitted steps;
    - anywhere else (eager single device, sep degree 1): dense
      attention fallback with identical semantics.
    """
    if _axis_bound(axis_name):
        if mode == "ring":
            return ring_attention(q, k, v, axis_name, causal)
        if mode == "zigzag":
            return ring_attention(q, k, v, axis_name, causal,
                                  layout="zigzag")
        return ulysses_attention(q, k, v, axis_name, causal)

    from .topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    from jax._src import core as _jax_core
    in_trace = not _jax_core.trace_state_clean()
    dims = dict(hcg.mesh.shape) if hcg is not None else {}
    sep = dims.get(axis_name, 1)
    if hcg is not None and in_trace and sep > 1:
        mp = dims.get("mp", 1)
        if q.shape[1] % sep:
            raise ValueError(
                f"sequence length {q.shape[1]} must divide the sep "
                f"degree {sep} for seq_parallel_mode")
        if mp > 1 and q.shape[2] % mp:
            raise ValueError(
                f"num_heads {q.shape[2]} must be divisible by the mp "
                f"degree {mp}")
        local_heads = q.shape[2] // mp
        if mode == "ulysses" and local_heads % sep:
            raise ValueError(
                "ulysses redistributes heads over sep: per-mp-shard "
                f"heads {local_heads} must be divisible by the sep "
                f"degree {sep}")
        from ..compat import shard_map
        head_axis = "mp" if mp > 1 else None

        def sharded(qq, kk, vv):
            # ring rotates K/V over sep; heads are a pure batch dim, so
            # an mp head-shard composes for free. Ulysses exchanges its
            # (mp-local) head shard against the sequence shard.
            if mode == "ring":
                return ring_attention(qq, kk, vv, axis_name, causal)
            if mode == "zigzag":
                # the caller (models.gpt boundary permutation) already
                # laid the sequence out in zigzag order, so contiguous
                # sep-sharding hands each device its zigzag shard
                return ring_attention(qq, kk, vv, axis_name, causal,
                                      layout="zigzag")
            return ulysses_attention(qq, kk, vv, axis_name, causal)

        try:
            manual = set(jax.sharding.get_abstract_mesh().manual_axes)
        except Exception:
            manual = set()
        if manual:
            # already inside a manual region (the pipeline's shard_map
            # over "pp"): nest a partial-manual shard_map over sep (+mp,
            # + the batch axes) on the CONTEXT abstract mesh (pp stays
            # manual outside). The batch axes join the manual set because
            # a Pallas (flash) hop requires every mesh axis around it to
            # be manual — attention is purely data-parallel in batch, so
            # the split is semantically free.
            amesh = jax.sharding.get_abstract_mesh()
            # manual over EVERY remaining axis (degree-1 ones are free):
            # Mosaic refuses to lower a Pallas call inside any auto-axis
            # context. The batch dim stays OUT of the specs (replicated
            # along dp/sharding in the manual region): marking an axis
            # manual does not require splitting data over it, and a
            # batch split would add a new divisibility precondition on
            # the per-stage microbatch.
            names = set(amesh.axis_names) - set(amesh.manual_axes)
            spec = P(None, axis_name, head_axis)
            return shard_map(sharded, mesh=amesh,
                             in_specs=spec, out_specs=spec,
                             check_vma=False,
                             axis_names=frozenset(names))(q, k, v)
        batch_axes = tuple(a for a in ("dp", "sharding")
                           if dims.get(a, 1) > 1) or None
        spec = P(batch_axes, axis_name, head_axis)
        return shard_map(sharded, mesh=hcg.mesh, in_specs=spec,
                         out_specs=spec, check_vma=False)(q, k, v)

    from ..ops.nn_functional import scaled_dot_product_attention
    if mode == "zigzag" and sep > 1:
        # The caller (models.gpt) hands zigzag-ordered tensors whenever
        # sep > 1; the dense fallback (eager path) must un-permute
        # before masking causally and re-permute the result, or the
        # row>=col mask would apply to reordered tokens.
        perm, inv = zigzag_permutation(q.shape[1], sep)
        out = scaled_dot_product_attention(
            q[:, inv], k[:, inv], v[:, inv], is_causal=causal,
            use_flash=False)
        return out[:, perm]
    return scaled_dot_product_attention(q, k, v, is_causal=causal,
                                        use_flash=False)
