"""paddle.distributed.spawn — run a function on N local ranks.

Reference parity: python/paddle/distributed/spawn.py (spawns worker
processes with the fleetrun env contract and joins them).

TPU-native note: SPMD training normally runs ONE process per host with
all chips visible (pjit over a Mesh) — spawn exists for the reference's
process-per-rank model and for CPU-mesh tests; each child gets the
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM env contract used by
distributed.env.ParallelEnv.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Sequence


def _worker(rank: int, nprocs: int, fn_name_queue, func, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["FLAGS_selected_devices"] = str(rank)
    try:
        func(*args)
        fn_name_queue.put((rank, None))
    except Exception:
        fn_name_queue.put((rank, traceback.format_exc()))


class SpawnContext:
    def __init__(self, procs, queue):
        self.processes = procs
        self._queue = queue

    def join(self, timeout=None):
        errs = []
        for _ in self.processes:
            rank, err = self._queue.get(timeout=timeout)
            if err:
                errs.append((rank, err))
        for p in self.processes:
            p.join(timeout)
        if errs:
            rank, err = errs[0]
            raise RuntimeError(f"spawned rank {rank} failed:\n{err}")
        return True


def spawn(func, args: Sequence = (), nprocs: int = -1, join: bool = True,
          daemon: bool = False, **options) -> SpawnContext:
    """reference: paddle.distributed.spawn(func, args, nprocs, join)."""
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
    q = ctx.Queue()
    env = {k: v for k, v in os.environ.items()
           if k.startswith(("PADDLE_", "FLAGS_", "XLA_", "JAX_"))}
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(rank, nprocs, q, func, tuple(args), env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    sctx = SpawnContext(procs, q)
    if join:
        sctx.join()
    return sctx
