"""Fleet: the distributed-training facade.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py
(fleet.init:139, distributed_optimizer, distributed_model, minimize:1244 +
the meta-optimizer stack under meta_optimizers/). The reference's
meta-optimizers rewrite a serialized program per feature; here every
feature is a sharding/remat/precision decision applied to ONE pjit-compiled
train step:

- data parallel      -> batch sharded over ("dp","sharding"); grad psum is
                        inserted by GSPMD (replaces imperative/reducer.cc)
- tensor parallel    -> param PartitionSpecs from mp_layers (replaces
                        TensorParallelOptimizer program rewrite)
- ZeRO sharding      -> optimizer-slot shardings over the sharding axis
                        (replaces sharding_optimizer.py:87 minimize_impl)
- recompute          -> jax.checkpoint around blocks (replaces
                        RecomputeOptimizer, fluid/optimizer.py:5288)
- amp                -> bf16 params/compute via amp.decorate / auto_cast
- gradient merge     -> micro-step accumulation inside the step (replaces
                        GradientMergeOptimizer, fluid/optimizer.py:6141)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..autograd.engine import no_grad
from ..core import rng as rng_mod
from ..nn.layer import Layer, bind_state, functional_state
from ..tensor import Tensor
from .env import get_rank, get_world_size, init_parallel_env
from .strategy import DistributedStrategy
from .topology import (HybridCommunicateGroup,
                       create_hybrid_communicate_group,
                       get_hybrid_communicate_group)

# fleet.util attribute (reference: fleet_base.py exposes UtilBase as a
# property — host collectives + filelist sharding for dataset/PS training)
from .fleet_util import fleet_util as _fleet_util_factory
util = _fleet_util_factory()

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None) -> None:
    """fleet.init (reference: fleet_base.py:139). Builds the hybrid mesh
    from strategy.hybrid_configs."""
    global _fleet_initialized, _strategy
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    cfg = _strategy.hybrid_configs
    n_dev = jax.device_count()
    degrees = {k: cfg.get(k, 1) for k in
               ("dp_degree", "mp_degree", "pp_degree", "sharding_degree",
                "sep_degree")}
    need = int(np.prod([max(1, d) for d in degrees.values()]))
    if degrees["dp_degree"] <= 0:  # auto-fill dp like the reference
        used = need // max(1, degrees["dp_degree"] or 1)
        used = int(np.prod([max(1, degrees[k]) for k in degrees
                            if k != "dp_degree"]))
        degrees["dp_degree"] = max(1, n_dev // used)
    create_hybrid_communicate_group(
        dp_degree=max(1, degrees["dp_degree"]),
        mp_degree=max(1, degrees["mp_degree"]),
        pp_degree=max(1, degrees["pp_degree"]),
        sharding_degree=max(1, degrees["sharding_degree"]),
        sep_degree=max(1, degrees["sep_degree"]))
    _fleet_initialized = True


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def is_first_worker() -> bool:
    return get_rank() == 0


def distributed_model(model: Layer) -> Layer:
    """Reference: fleet.distributed_model wraps a Layer for DDP/hybrid.
    In SPMD-jit execution the model is unchanged — sharding comes from the
    train step — so this validates and returns the model."""
    return model


class _DistributedOptimizer:
    """Wrapper marking an optimizer for use inside the sharded step
    (reference: fleet.distributed_optimizer + HybridParallelOptimizer)."""

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = optimizer
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy]
                          = None):
    strategy = strategy or _strategy or DistributedStrategy()
    if strategy.dgc:
        # reference: DGCOptimizer meta-optimizer swaps Momentum for
        # DGCMomentum (meta_optimizers/dgc_optimizer.py)
        from ..optimizer import DGCMomentum, Momentum
        if isinstance(optimizer, Momentum) and \
                not isinstance(optimizer, DGCMomentum):
            cfg = strategy.dgc_configs
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                use_nesterov=optimizer._nesterov,
                weight_decay=optimizer._weight_decay,
                grad_clip=optimizer._grad_clip)
    return _DistributedOptimizer(optimizer, strategy)


# ---------------------------------------------------------------------------
# The sharded train step — where all meta-optimizer features land
# ---------------------------------------------------------------------------

def make_functional_loss(model: Layer, train_fn: Callable) -> Callable:
    """Adapt eager-style ``train_fn(model, batch) -> loss`` into the pure
    ``loss_of(params, buffers, key, batch) -> (loss, new_buffers)`` form
    every train step differentiates."""

    def loss_of(p, buffers, key, batch):
        model.train()
        with bind_state(model, {"params": p, "buffers": buffers}), \
                no_grad(), rng_mod.key_scope(key):
            loss = train_fn(model, jax.tree_util.tree_map(
                lambda v: Tensor(v) if isinstance(v, jax.Array) else v,
                batch))
            new_buf = {n: b.value for n, b in model.named_buffers()
                       if b is not None}
        raw = loss.value if isinstance(loss, Tensor) else loss
        return raw, new_buf

    return loss_of

def _param_sharding(mesh: Mesh, name: str, value, pspec,
                    zero_axis: Optional[str]) -> NamedSharding:
    if pspec is not None:
        return NamedSharding(mesh, pspec)
    if zero_axis is not None:
        # ZeRO-3-style param sharding: shard dim0 over the sharding axis
        size = mesh.shape[zero_axis]
        if value.ndim > 0 and value.shape[0] % size == 0 and \
                value.shape[0] >= size:
            return NamedSharding(mesh, P(zero_axis))
    return NamedSharding(mesh, P())


def _slot_sharding(mesh: Mesh, param_sharding: NamedSharding, value,
                   shard_axis: Optional[str]) -> NamedSharding:
    """Optimizer slots follow their param, plus ZeRO-1 sharding over the
    sharding axis when enabled and shapes divide."""
    spec = param_sharding.spec
    if spec and len(spec) > 0 and spec[0] is not None:
        return NamedSharding(mesh, spec)
    if shard_axis is not None and value.ndim > 0:
        size = mesh.shape[shard_axis]
        if value.shape[0] % size == 0 and value.shape[0] >= size:
            rest = list(spec[1:]) if spec else [None] * (value.ndim - 1)
            return NamedSharding(mesh, P(shard_axis, *rest))
    return NamedSharding(mesh, spec if spec else P())


class ShardedTrainStep:
    """pjit-compiled hybrid-parallel train step.

    The single-device TrainStep's structure (forward + jax.grad + update in
    one XLA program), with GSPMD sharding over the fleet mesh. Data enters
    sharded over (dp × sharding); params/slots carry their TP/ZeRO specs;
    XLA inserts all collectives (grad psum over dp, TP all-reduces, ZeRO
    all-gathers) and overlaps them with compute.
    """

    def __init__(self, model: Layer, optimizer, train_fn: Callable,
                 hcg: Optional[HybridCommunicateGroup] = None,
                 strategy: Optional[DistributedStrategy] = None,
                 donate: bool = True, seed: int = 0,
                 batch_spec: Optional[P] = None):
        if isinstance(optimizer, _DistributedOptimizer):
            optimizer = optimizer._inner
        self.model = model
        self.optimizer = optimizer
        self.train_fn = train_fn
        self.hcg = hcg or get_hybrid_communicate_group()
        if self.hcg is None:
            raise RuntimeError("call fleet.init(strategy) first")
        self.strategy = strategy or _strategy or DistributedStrategy()
        mesh = self.hcg.mesh
        self.mesh = mesh

        zero_stage = 0
        if self.strategy.sharding:
            zero_stage = int(self.strategy.sharding_configs.get("stage", 1))
        shard_axis = "sharding" if (self.strategy.sharding and
                                    self.hcg.dims["sharding"] > 1) else None

        state = functional_state(model)
        named_params = dict(model.named_parameters())
        self.param_shardings = {
            n: _param_sharding(mesh, n, v,
                               getattr(named_params.get(n), "pspec", None),
                               shard_axis if zero_stage >= 3 else None)
            for n, v in state["params"].items()}
        # buffers default replicated, but honor an explicit pspec (a
        # weight-only-int8 buffer converted from a TP linear keeps its
        # mp sharding)
        named_buffers = dict(model.named_buffers())
        self.buffer_shardings = {}
        for n in state["buffers"]:
            bspec = getattr(named_buffers.get(n), "pspec", None)
            self.buffer_shardings[n] = NamedSharding(
                mesh, bspec if bspec is not None else P())
        self.params = {n: jax.device_put(v, self.param_shardings[n])
                       for n, v in state["params"].items()}
        self.buffers = {n: jax.device_put(v, self.buffer_shardings[n])
                        for n, v in state["buffers"].items()}

        opt_state = optimizer.init(self.params)
        self.opt_shardings = {
            "slots": {n: {k: _slot_sharding(mesh, self.param_shardings[n],
                                            v, shard_axis)
                          for k, v in slots.items()}
                      for n, slots in opt_state["slots"].items()},
            "step": NamedSharding(mesh, P())}
        self.opt_state = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(v, s), opt_state,
            {"slots": self.opt_shardings["slots"],
             "step": self.opt_shardings["step"]},
            is_leaf=lambda x: isinstance(x, jax.Array))

        # batch: dim0 over dp×sharding (reference: DistributedBatchSampler
        # feeds disjoint shards; here one global array is split by GSPMD)
        if batch_spec is None:
            data_axes = tuple(a for a in ("dp", "sharding")
                              if mesh.shape[a] > 1) or ("dp",)
            batch_spec = P(data_axes if len(data_axes) > 1 else
                           data_axes[0])
        self.batch_spec = batch_spec
        self._key = jax.random.key(seed)

        gm_steps = 1
        if self.strategy.gradient_merge:
            gm_steps = int(self.strategy.gradient_merge_configs.get(
                "k_steps", 1))
        self._gm_steps = max(1, gm_steps)

        # Optimizer-state host offload (reference:
        # sharding/offload_helper.py:21): slots live in pinned host
        # memory between steps; the step splits into a grad phase (slots
        # absent from HBM while activations peak) and an update phase
        # (slots staged in, updated, staged back out).
        if self.strategy.sharding_configs.get("optimize_offload") and \
                not self.strategy.sharding:
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                "sharding_configs.optimize_offload requires "
                "strategy.sharding = True (it must not silently no-op)")
        self._offload = bool(
            self.strategy.sharding
            and self.strategy.sharding_configs.get("optimize_offload"))
        if self._offload:
            self._host_slot_shardings = jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("pinned_host"),
                self.opt_shardings["slots"])
            self.opt_state["slots"] = jax.device_put(
                self.opt_state["slots"], self._host_slot_shardings)

        self._compress_grads = bool(self.strategy.fp16_allreduce)
        if self._compress_grads:
            for ax in ("mp", "pp", "sep", "sharding"):
                if self.hcg.dims.get(ax, 1) > 1:
                    raise ValueError(
                        "fp16_allreduce compresses the data-parallel "
                        f"gradient exchange; {ax} degree must be 1 "
                        "(matches the reference meta-optimizer's "
                        "conflict rules)")

        self._step = self._build(donate)

    def _batch_sharding(self, batch_raw):
        mesh, spec = self.mesh, self.batch_spec

        def shard_of(x):
            if hasattr(x, "ndim") and x.ndim >= 1:
                return NamedSharding(mesh, spec)
            return NamedSharding(mesh, P())

        return jax.tree_util.tree_map(shard_of, batch_raw)

    def _build(self, donate: bool):
        model, optimizer, train_fn = self.model, self.optimizer, \
            self.train_fn
        gm = self._gm_steps

        loss_of = make_functional_loss(model, train_fn)
        if self.strategy.recompute and \
                self.strategy.recompute_configs.get("enable_offload"):
            # Activation offload (reference recompute_configs
            # .enable_offload) is implemented on the remat path
            # (core/offload.py: checkpointed block inputs stage to
            # pinned host memory) and works on the single-chip TrainStep;
            # composed with GSPMD it trips XLA's SPMD partitioner
            # (annotate_device_placement without sharding, RET_CHECK at
            # spmd_partitioner.cc:5743), so the sharded path refuses
            # instead of crashing mid-compile.
            from ..core.enforce import UnimplementedError
            raise UnimplementedError(
                "recompute_configs.enable_offload under the sharded "
                "(GSPMD) step: XLA's SPMD partitioner rejects host-"
                "offload annotations from this composition. Use "
                "sharding_configs.optimize_offload (optimizer-state "
                "offload) here; activation offload is available on the "
                "single-chip TrainStep via "
                "core.offload.set_activation_offload(True).")

        mesh, bspec = self.mesh, self.batch_spec
        data_axes: list = []
        for e in bspec:
            if e is None:
                continue
            data_axes.extend(e if isinstance(e, (tuple, list)) else [e])
        data_axes = tuple(data_axes)
        nrep = int(np.prod([mesh.shape[a] for a in data_axes])) or 1

        if self._compress_grads:
            # bf16-compressed dp gradient exchange: grads computed
            # per-shard under shard_map and psum'd in bf16 (reference:
            # fp16_allreduce_optimizer.py casts before c_allreduce; bf16
            # is the TPU-native low-precision reduction format).
            # DDP convention: global grad = MEAN of per-shard grads, so
            # train_fn must return a batch-mean loss; a sum-reduced loss
            # comes out scaled by 1/dp relative to the exact path.
            from ..compat import shard_map as _shard_map
            from .mp_layers import no_sharding_constraints

            def vag(params, buffers, key, batch):
                def per_shard(p, b, k, local_batch):
                    idx = jnp.zeros((), jnp.int32)
                    for ax in data_axes:
                        idx = idx * mesh.shape[ax] + \
                            jax.lax.axis_index(ax)
                    k = jax.random.fold_in(k, idx)
                    with no_sharding_constraints():
                        (loss, nb), g = jax.value_and_grad(
                            loss_of, has_aux=True)(p, b, k, local_batch)
                    g = jax.tree_util.tree_map(
                        lambda x: jax.lax.psum(
                            x.astype(jnp.bfloat16),
                            data_axes).astype(x.dtype) / nrep, g)
                    loss = jax.lax.pmean(loss, data_axes)
                    nb = jax.tree_util.tree_map(
                        lambda x: jax.lax.pmean(x, data_axes)
                        if jnp.issubdtype(x.dtype, jnp.inexact)
                        else jax.lax.pmax(x, data_axes), nb)
                    return (loss, nb), g

                batch_specs = jax.tree_util.tree_map(
                    lambda v: P(*tuple(bspec))
                    if getattr(v, "ndim", 0) >= 1 else P(), batch)
                sm = _shard_map(per_shard, mesh=mesh,
                                in_specs=(P(), P(), P(), batch_specs),
                                out_specs=((P(), P()), P()),
                                check_vma=False)
                return sm(params, buffers, key, batch)
        else:
            def vag(params, buffers, key, batch):
                return jax.value_and_grad(loss_of, has_aux=True)(
                    params, buffers, key, batch)

        def grad_impl(params, buffers, key, batch):
            # evolve the key inside the launch: one dispatch per step
            # (a host-side split is a separate device round-trip)
            key, new_key = jax.random.split(key)
            if gm > 1:
                # gradient merge: split the batch into k micro-steps and
                # accumulate grads (reference GradientMergeOptimizer)
                def micro(i, carry):
                    acc, buf, k = carry
                    k, sub = jax.random.split(k)
                    mb = jax.tree_util.tree_map(
                        lambda v: jnp.reshape(
                            v, (gm, v.shape[0] // gm) + v.shape[1:])[i]
                        if hasattr(v, "ndim") and v.ndim >= 1 else v, batch)
                    (loss, nb), g = vag(params, buf, sub, mb)
                    acc = jax.tree_util.tree_map(jnp.add, acc, g)
                    return (acc, nb, k)

                zero = jax.tree_util.tree_map(jnp.zeros_like, params)
                grads, new_buf, _ = jax.lax.fori_loop(
                    0, gm, micro, (zero, buffers, key))
                grads = jax.tree_util.tree_map(lambda g: g / gm, grads)
                loss = jnp.zeros((), jnp.float32)
            else:
                (loss, new_buf), grads = vag(params, buffers, key, batch)
            return grads, new_buf, new_key, loss

        scalar = NamedSharding(self.mesh, P())
        slots_sh = {"slots": self.opt_shardings["slots"],
                    "step": self.opt_shardings["step"]}

        if self._offload:
            # split step: grads with slots out of HBM, then the update.
            # Slot staging happens at the Python level (device_put before
            # /after the update jit): in-program host transfers
            # (annotate_device_placement) and host-space compute are both
            # rejected by the CPU test backend, so the jit boundary IS
            # the transfer point.
            def update_impl(params, grads, opt_state, lr):
                return optimizer.apply_gradients(params, grads,
                                                 opt_state, lr=lr)

            grad_step = jax.jit(
                grad_impl,
                in_shardings=(self.param_shardings,
                              self.buffer_shardings, scalar, None),
                out_shardings=(self.param_shardings,
                               self.buffer_shardings, scalar, scalar),
                **({"donate_argnums": (1,)} if donate else {}))
            # donate params + slots (aliased by the two param-sized
            # outputs); grads have no matching output, donating them
            # would only trigger the unused-donation warning
            update_step = jax.jit(
                update_impl,
                in_shardings=(self.param_shardings,
                              self.param_shardings, slots_sh, scalar),
                out_shardings=(self.param_shardings, slots_sh),
                **({"donate_argnums": (0, 2)} if donate else {}))
            dev_slots = self.opt_shardings["slots"]
            host_slots = self._host_slot_shardings

            def offload_step(params, buffers, opt_state, key, lr, batch):
                grads, new_buf, new_key, loss = grad_step(
                    params, buffers, key, batch)
                staged = {"slots": jax.device_put(opt_state["slots"],
                                                  dev_slots),
                          "step": opt_state["step"]}
                new_params, new_opt = update_step(params, grads, staged,
                                                  lr)
                new_opt = {"slots": jax.device_put(new_opt["slots"],
                                                   host_slots),
                           "step": new_opt["step"]}
                return new_params, new_buf, new_opt, new_key, loss

            return offload_step

        def step_impl(params, buffers, opt_state, key, lr, batch):
            grads, new_buf, new_key, loss = grad_impl(params, buffers,
                                                      key, batch)
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr=lr)
            return new_params, new_buf, new_opt, new_key, loss

        in_shardings = (self.param_shardings, self.buffer_shardings,
                        slots_sh, scalar, scalar)
        out_shardings = (self.param_shardings, self.buffer_shardings,
                         slots_sh, scalar, scalar)
        kwargs = {"donate_argnums": (0, 1, 2)} if donate else {}
        return jax.jit(step_impl,
                       in_shardings=in_shardings + (None,),
                       out_shardings=out_shardings, **kwargs)

    def _lr_device(self):
        from ..jit import cached_lr_device
        return cached_lr_device(self, self.optimizer)

    def __call__(self, batch):
        from ..jit import effects_token_guard
        effects_token_guard(self.mesh.devices.flat)
        batch_raw = jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, batch,
            is_leaf=lambda t: isinstance(t, Tensor))
        batch_raw = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(jnp.asarray(v), s),
            batch_raw, self._batch_sharding(batch_raw))
        self.params, self.buffers, self.opt_state, self._key, loss = \
            self._step(self.params, self.buffers, self.opt_state,
                       self._key, self._lr_device(), batch_raw)
        return loss

    def sync_to_model(self) -> None:
        named_p = dict(self.model.named_parameters())
        for n, v in self.params.items():
            if n in named_p:
                named_p[n].value = v
        named_b = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in named_b:
                named_b[n].value = v


def distributed_jit(model: Layer, optimizer, train_fn: Callable,
                    **kwargs):
    """Build the train step for the current fleet mesh. When the
    strategy enables localsgd, this returns a LocalSGDTrainStep (the
    reference's LocalSGD meta-optimizer path); otherwise the SPMD
    ShardedTrainStep."""
    strategy = kwargs.get("strategy") or _strategy
    if strategy is not None and (strategy.localsgd or
                                 strategy.adaptive_localsgd):
        from ..core.enforce import UnimplementedError
        if strategy.sharding_configs.get("optimize_offload") or (
                strategy.recompute
                and strategy.recompute_configs.get("enable_offload")):
            raise UnimplementedError(
                "offload (sharding_configs.optimize_offload / "
                "recompute_configs.enable_offload) is not implemented "
                "for the localsgd step — it must not silently no-op")
        from .localsgd import LocalSGDTrainStep
        if kwargs.get("batch_spec") is not None:
            raise ValueError(
                "batch_spec is not supported with localsgd (replica "
                "batches shard over dp only)")
        if isinstance(optimizer, _DistributedOptimizer):
            optimizer = optimizer._inner
        cfg = strategy.localsgd_configs
        return LocalSGDTrainStep(
            model, optimizer, train_fn,
            k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 1),
            adaptive=bool(strategy.adaptive_localsgd),
            hcg=kwargs.get("hcg"), seed=kwargs.get("seed", 0),
            donate=kwargs.get("donate", True))
    return ShardedTrainStep(model, optimizer, train_fn, **kwargs)


# -- reference-parity class surface ------------------------------------------

from . import meta_parallel  # noqa: E402,F401
from . import fleet_utils as utils  # noqa: E402,F401
from .data_generator import (DataGenerator,  # noqa: E402,F401
                             MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)
from .fleet_util import UtilBase  # noqa: E402,F401
from .role_maker import (PaddleCloudRoleMaker, Role,  # noqa: E402,F401
                         RoleMakerBase, UserDefinedRoleMaker)
from .topology import CommunicateTopology  # noqa: E402,F401


class Fleet:
    """Class facade over this module's fleet functions (reference:
    fleet/base/fleet_base.py Fleet — there the singleton
    ``paddle.distributed.fleet`` IS a Fleet instance; here the module is
    the singleton and this class delegates for API parity)."""

    def __init__(self):
        self._role_maker = None

    def init(self, role_maker=None, is_collective: bool = False,
             strategy=None):
        # reference Fleet.init defaults is_collective=False
        # (fleet/base/fleet_base.py:139) — PS users calling Fleet().init()
        # must not silently get collective mode. The module-level init()
        # keeps its TPU-mainline default of True.
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        return init(role_maker, is_collective, strategy)

    def is_first_worker(self) -> bool:
        return is_first_worker()

    def worker_index(self) -> int:
        return worker_index()

    def worker_num(self) -> int:
        return worker_num()

    def is_worker(self) -> bool:
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self) -> bool:
        return self._role_maker is not None and self._role_maker.is_server()

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    @property
    def util(self):
        from .fleet_util import fleet_util
        return fleet_util()
