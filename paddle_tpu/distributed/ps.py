"""Parameter-server training mode.

Reference parity: paddle/fluid/distributed/ (brpc PS:
service/brpc_ps_server.cc, brpc_ps_client.cc; tables
table/common_dense_table.cc, common_sparse_table.cc; async grad
Communicator service/communicator.cc; Python runtime
fleet/runtime/the_one_ps.py:434).

This build: the same wire protocol shape (push/pull dense + sparse,
sync/async/geo modes, id-sharded tables across servers) over a
length-prefixed socket RPC. Two transports share one client surface:

- ``PSServer``/``PSClient`` — Python sockets + pickle; hosts every table
  kind including the sqlite-backed ``SSDSparseTable``.
- ``NativePSServer``/``NativePSClient`` — the C++ service
  (native/pt_ps.cc): binary protocol, threaded POSIX-socket server,
  dense SGD/Adam + sparse SGD/Adagrad/geo-delta applied in C++ (the
  brpc_ps_server.cc equivalent; no pickle on the hot path).

PS mode is a CPU-side capability (huge sparse embeddings); the
TPU-native mainline is the collective path. Protocol constants mirror
distributed/ps.proto.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# message kinds (mirrors the PsCmdID idea in distributed/ps.proto)
PULL_DENSE = "pull_dense"
PUSH_DENSE = "push_dense"
PULL_SPARSE = "pull_sparse"
PUSH_SPARSE = "push_sparse"
PUSH_SPARSE_DELTA = "push_sparse_delta"  # geo-SGD delta apply
BARRIER = "barrier"
STOP = "stop"
STAT = "stat"


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class DenseTable:
    """reference: table/common_dense_table.cc — full replica on its
    server, SGD/Adam applied server-side on push_grad."""

    def __init__(self, shape, optimizer: str = "sgd", lr: float = 0.01,
                 beta1=0.9, beta2=0.999, eps=1e-8):
        self.value = np.zeros(shape, np.float32)
        self.optimizer = optimizer
        self.lr = lr
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self._m = np.zeros(shape, np.float32)
        self._v = np.zeros(shape, np.float32)
        self._t = 0
        self._lock = threading.Lock()

    def init(self, value: np.ndarray) -> None:
        with self._lock:
            self.value = np.asarray(value, np.float32).copy()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.value.copy()

    def push_grad(self, grad: np.ndarray) -> None:
        with self._lock:
            g = np.asarray(grad, np.float32)
            if self.optimizer == "adam":
                self._t += 1
                self._m = self.beta1 * self._m + (1 - self.beta1) * g
                self._v = self.beta2 * self._v + (1 - self.beta2) * g * g
                mh = self._m / (1 - self.beta1 ** self._t)
                vh = self._v / (1 - self.beta2 ** self._t)
                self.value -= self.lr * mh / (np.sqrt(vh) + self.eps)
            else:
                self.value -= self.lr * g


class SparseTable:
    """reference: table/common_sparse_table.cc — rows created on first
    access (the trillion-parameter embedding pattern), per-row adagrad."""

    def __init__(self, emb_dim: int, lr: float = 0.01,
                 initializer_std: float = 0.01, optimizer: str = "adagrad"):
        self.emb_dim = emb_dim
        self.lr = lr
        self.std = initializer_std
        self.optimizer = optimizer
        self.rows: Dict[int, np.ndarray] = {}
        self.accum: Dict[int, np.ndarray] = {}
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(0)

    def _row(self, key: int) -> np.ndarray:
        r = self.rows.get(key)
        if r is None:
            r = (self._rng.standard_normal(self.emb_dim) *
                 self.std).astype(np.float32)
            self.rows[key] = r
            self.accum[key] = np.zeros(self.emb_dim, np.float32)
        return r

    def pull(self, keys: Sequence[int]) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(k)) for k in keys])

    def push_grad(self, keys: Sequence[int], grads: np.ndarray) -> None:
        with self._lock:
            for k, g in zip(keys, np.asarray(grads, np.float32)):
                k = int(k)
                self._row(k)
                if self.optimizer == "adagrad":
                    self.accum[k] += g * g
                    self.rows[k] -= self.lr * g / (
                        np.sqrt(self.accum[k]) + 1e-6)
                else:
                    self.rows[k] -= self.lr * g

    def size(self) -> int:
        with self._lock:
            return len(self.rows)

    def push_delta(self, keys: Sequence[int], deltas: np.ndarray) -> None:
        """Geo-SGD apply: value += delta (ref table/sparse_geo_table.cc —
        trainers train local replicas and ship parameter deltas, not
        gradients)."""
        with self._lock:
            for k, d in zip(keys, np.asarray(deltas, np.float32)):
                self._row(int(k))
                self.rows[int(k)] += d


class SSDSparseTable:
    """Disk-backed sparse table: sqlite3 store + write-through LRU cache
    (ref table/ssd_sparse_table.cc over RocksDB — embeddings larger than
    host RAM). Same pull/push_grad/push_delta surface as SparseTable;
    rows persist value||accum so adagrad state survives eviction."""

    def __init__(self, emb_dim: int, lr: float = 0.01,
                 initializer_std: float = 0.01, optimizer: str = "adagrad",
                 path: str = ":memory:", cache_rows: int = 100_000):
        import sqlite3
        self.emb_dim = emb_dim
        self.lr = lr
        self.std = initializer_std
        self.optimizer = optimizer
        self.cache_rows = cache_rows
        # autocommit: evicted rows must survive a server crash/stop
        # without an explicit flush
        self._db = sqlite3.connect(path, check_same_thread=False,
                                   isolation_level=None)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rows (k INTEGER PRIMARY KEY, "
            "v BLOB)")
        self._cache: Dict[int, np.ndarray] = {}  # insertion-ordered LRU
        self._dirty: set = set()
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(0)

    def _load(self, key: int) -> np.ndarray:
        """Return [2, emb_dim] (value row, adagrad accum row)."""
        row = self._cache.pop(key, None)
        if row is None:
            cur = self._db.execute("SELECT v FROM rows WHERE k=?", (key,))
            hit = cur.fetchone()
            if hit is not None:
                row = np.frombuffer(hit[0], np.float32).reshape(
                    2, self.emb_dim).copy()
            else:
                row = np.stack([
                    (self._rng.standard_normal(self.emb_dim) *
                     self.std).astype(np.float32),
                    np.zeros(self.emb_dim, np.float32)])
                self._dirty.add(key)
        self._cache[key] = row  # re-insert = most recently used
        self._evict()
        return row

    def _evict(self) -> None:
        while len(self._cache) > self.cache_rows:
            k, row = next(iter(self._cache.items()))
            del self._cache[k]
            if k in self._dirty:
                self._write(k, row)
                self._dirty.discard(k)

    def _write(self, key: int, row: np.ndarray) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO rows (k, v) VALUES (?, ?)",
            (key, row.tobytes()))

    def flush(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            # one transaction, not one fsync per row
            self._db.execute("BEGIN")
            try:
                self._db.executemany(
                    "INSERT OR REPLACE INTO rows (k, v) VALUES (?, ?)",
                    [(k, self._cache[k].tobytes()) for k in self._dirty])
                self._db.execute("COMMIT")
            except BaseException:
                try:
                    self._db.execute("ROLLBACK")
                except Exception:
                    pass  # keep the original write error, not the rollback's
                raise
            self._dirty.clear()

    def pull(self, keys: Sequence[int]) -> np.ndarray:
        with self._lock:
            return np.stack([self._load(int(k))[0] for k in keys])

    def push_grad(self, keys: Sequence[int], grads: np.ndarray) -> None:
        with self._lock:
            for k, g in zip(keys, np.asarray(grads, np.float32)):
                k = int(k)
                row = self._load(k)
                if self.optimizer == "adagrad":
                    row[1] += g * g
                    row[0] -= self.lr * g / (np.sqrt(row[1]) + 1e-6)
                else:
                    row[0] -= self.lr * g
                self._dirty.add(k)

    def push_delta(self, keys: Sequence[int], deltas: np.ndarray) -> None:
        with self._lock:
            for k, d in zip(keys, np.asarray(deltas, np.float32)):
                k = int(k)
                self._load(k)[0] += d
                self._dirty.add(k)

    def size(self) -> int:
        self.flush()
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM rows").fetchone()[0]


class PSServer:
    """reference: service/brpc_ps_server.cc — hosts tables, serves
    push/pull RPCs on a thread-per-connection server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.dense: Dict[str, DenseTable] = {}
        self.sparse: Dict[str, SparseTable] = {}
        self.graph: Dict[str, "GraphTable"] = {}
        self._barrier_count = 0
        self._barrier_lock = threading.Lock()
        # Blocking rendezvous barrier (sync-PS lockstep, reference:
        # brpc_ps_server barrier service): arrivals wait until `world`
        # trainers reach the same generation.
        self._rdv_cv = threading.Condition()
        self._rdv_arrived = 0
        self._rdv_generation = 0
        # Handler threads are daemonic and may sit blocked in _recv_msg on
        # idle connections, so stop() cannot join them. Instead dispatches
        # are counted: stop() flips _stopping (new mutations get a NACK,
        # never an ack that could be lost) and drains in-flight ones
        # before flushing tables.
        self._stopping = False
        self._active = 0
        self._active_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        with outer._active_cv:
                            admitted = (not outer._stopping
                                        or msg.get("cmd") == STOP)
                            if admitted:
                                outer._active += 1
                        if admitted:
                            try:
                                resp = outer._dispatch(msg)
                            finally:
                                with outer._active_cv:
                                    outer._active -= 1
                                    outer._active_cv.notify_all()
                        else:
                            resp = {"ok": False, "error": "server stopping"}
                        _send_msg(self.request, resp)
                        if msg.get("cmd") == STOP:
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def add_dense_table(self, name: str, shape, **kw) -> DenseTable:
        t = DenseTable(shape, **kw)
        self.dense[name] = t
        return t

    def add_sparse_table(self, name: str, emb_dim: int,
                         kind: str = "mem", **kw):
        """kind: 'mem' (common_sparse_table) or 'ssd'
        (ssd_sparse_table, disk-backed)."""
        t = (SSDSparseTable(emb_dim, **kw) if kind == "ssd"
             else SparseTable(emb_dim, **kw))
        self.sparse[name] = t
        return t

    def add_graph_table(self, name: str, feat_dim: int = 0
                        ) -> "GraphTable":
        """reference: common_graph_table.cc registered as a PS table."""
        t = GraphTable(feat_dim)
        self.graph[name] = t
        return t

    def _dispatch(self, msg: Dict) -> Dict:
        cmd = msg.get("cmd")
        try:
            if cmd == PULL_DENSE:
                return {"ok": True,
                        "value": self.dense[msg["table"]].pull()}
            if cmd == PUSH_DENSE:
                if msg.get("init"):
                    self.dense[msg["table"]].init(msg["grad"])
                else:
                    self.dense[msg["table"]].push_grad(msg["grad"])
                return {"ok": True}
            if cmd == PULL_SPARSE:
                return {"ok": True,
                        "value": self.sparse[msg["table"]].pull(
                            msg["keys"])}
            if cmd == PUSH_SPARSE:
                self.sparse[msg["table"]].push_grad(msg["keys"],
                                                    msg["grad"])
                return {"ok": True}
            if cmd == PUSH_SPARSE_DELTA:
                self.sparse[msg["table"]].push_delta(msg["keys"],
                                                     msg["delta"])
                return {"ok": True}
            if cmd == STAT:
                return {"ok": True,
                        "dense": list(self.dense),
                        "sparse": {k: v.size()
                                   for k, v in self.sparse.items()}}
            if cmd == BARRIER:
                world = int(msg.get("world", 0))
                if world > 1:
                    # blocking rendezvous: wait for `world` arrivals
                    with self._rdv_cv:
                        gen = self._rdv_generation
                        self._rdv_arrived += 1
                        if self._rdv_arrived >= world:
                            self._rdv_arrived = 0
                            self._rdv_generation += 1
                            self._rdv_cv.notify_all()
                        else:
                            while (self._rdv_generation == gen
                                   and not self._stopping):
                                self._rdv_cv.wait(timeout=1.0)
                with self._barrier_lock:
                    self._barrier_count += 1
                    n = self._barrier_count
                return {"ok": True, "count": n}
            if cmd == GRAPH_ADD_NODES:
                self.graph[msg["table"]].add_nodes(msg["ids"],
                                                  msg.get("feats"))
                return {"ok": True}
            if cmd == GRAPH_ADD_EDGES:
                self.graph[msg["table"]].add_edges(msg["srcs"],
                                                  msg["dsts"],
                                                  msg.get("weights"))
                return {"ok": True}
            if cmd == GRAPH_REMOVE_NODES:
                self.graph[msg["table"]].remove_nodes(msg["ids"])
                return {"ok": True}
            if cmd == GRAPH_SAMPLE_NEIGHBORS:
                nbrs, cnt = self.graph[msg["table"]].sample_neighbors(
                    msg["ids"], msg["sample_size"], msg.get("seed", 0))
                return {"ok": True, "neighbors": nbrs, "counts": cnt}
            if cmd == GRAPH_SAMPLE_NODES:
                return {"ok": True,
                        "ids": self.graph[msg["table"]].sample_nodes(
                            msg["n"], msg.get("seed", 0))}
            if cmd == GRAPH_GET_FEAT:
                return {"ok": True,
                        "feats": self.graph[msg["table"]].get_feat(
                            msg["ids"])}
            if cmd == GRAPH_LIST:
                return {"ok": True,
                        "ids": self.graph[msg["table"]].node_list(
                            msg["start"], msg["size"])}
            if cmd == STOP:
                return {"ok": True}
        except KeyError as e:
            return {"ok": False, "error": f"unknown table {e}"}
        except Exception as e:  # noqa: BLE001 - a handler thread must
            # always answer; the client re-raises the message
            return {"ok": False,
                    "error": f"{type(e).__name__}: {e}"}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # Order matters for durability: refuse new mutations, drain the
        # in-flight ones, then flush — no acknowledged push can land
        # behind the flush and get lost.
        with self._active_cv:
            self._stopping = True
            drained = self._active_cv.wait_for(
                lambda: self._active == 0, timeout=30)
        if not drained:
            import warnings
            warnings.warn(
                "PSServer.stop: in-flight requests did not drain within "
                "30s; flushing anyway — a late mutation may complete "
                "after the flush", RuntimeWarning)
        self._server.shutdown()
        self._server.server_close()
        for t in self.sparse.values():
            if hasattr(t, "flush"):
                t.flush()
        if not drained:
            # second flush after shutdown closes the socket loop: any
            # dispatch that slipped past the first flush has finished or
            # been torn down by now, so this pass catches its writes.
            with self._active_cv:
                self._active_cv.wait_for(lambda: self._active == 0,
                                         timeout=5)
            for t in self.sparse.values():
                if hasattr(t, "flush"):
                    t.flush()

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"


class PSClient:
    """reference: service/brpc_ps_client.cc — connects to all servers;
    sparse keys shard by key %% n_servers, dense tables live on
    table-hash-selected servers.

    Transport failures (ConnectionError/OSError — a restarted or
    preempted server) drop the wedged socket and RECONNECT under the
    per-site RetryPolicy ("ps.push"/"ps.pull"/"ps.call"), mirroring the
    brpc client's retry config. Semantics under retry: pulls are
    idempotent; pushes are at-least-once (a push whose ack was lost may
    be applied twice) — the same contract as the reference's async PS.
    Server-side errors (unknown table etc.) raise RuntimeError and are
    never retried."""

    def __init__(self, endpoints: Sequence[str], retry=None):
        # connections are LAZY (first _call connects under the site's
        # retry policy): constructing a client while one server is
        # mid-restart must not fail un-retried
        self.endpoints = list(endpoints)
        self._retry = retry
        self._socks: List[Optional[socket.socket]] = \
            [None] * len(self.endpoints)
        self._locks: List[threading.Lock] = \
            [threading.Lock() for _ in self.endpoints]

    def _connect_locked(self, server: int) -> None:
        host, _, port = self.endpoints[server].partition(":")
        self._socks[server] = socket.create_connection(
            (host, int(port)), timeout=30)

    def _call(self, server: int, msg: Dict, site: str = "ps.call") -> Dict:
        from .fault_inject import fault_point
        from .resilience import get_retry_policy

        def _once() -> Dict:
            fault_point(site)
            with self._locks[server]:
                sock = self._socks[server]
                try:
                    if sock is None:
                        self._connect_locked(server)
                        sock = self._socks[server]
                    _send_msg(sock, msg)
                    resp = _recv_msg(sock)
                except (ConnectionError, OSError):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    self._socks[server] = None  # reconnect on retry
                    raise
            if not resp.get("ok"):
                raise RuntimeError(resp.get("error"))
            return resp

        policy = self._retry or get_retry_policy(site)
        return policy.call(_once, site=site)

    def _dense_server(self, table: str) -> int:
        # stable across processes (built-in hash() is salted per process,
        # which would route the same table to different servers on
        # different trainers)
        import zlib
        return zlib.crc32(table.encode()) % len(self.endpoints)

    def push_dense_init(self, table: str, value: np.ndarray) -> None:
        self._call(self._dense_server(table),
                   {"cmd": PUSH_DENSE, "table": table, "grad": value,
                    "init": True}, site="ps.push")

    def pull_dense(self, table: str) -> np.ndarray:
        return self._call(self._dense_server(table),
                          {"cmd": PULL_DENSE, "table": table},
                          site="ps.pull")["value"]

    def push_dense_grad(self, table: str, grad: np.ndarray) -> None:
        self._call(self._dense_server(table),
                   {"cmd": PUSH_DENSE, "table": table, "grad": grad},
                   site="ps.push")

    def pull_sparse(self, table: str, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        n = len(self.endpoints)
        out = np.zeros((keys.size, 0), np.float32)
        results: Dict[int, np.ndarray] = {}
        for srv in range(n):
            mask = (keys % n) == srv
            if not mask.any():
                continue
            vals = self._call(srv, {"cmd": PULL_SPARSE, "table": table,
                                    "keys": keys[mask].tolist()},
                              site="ps.pull")["value"]
            results[srv] = vals
        dim = next(iter(results.values())).shape[1]
        full = np.zeros((keys.size, dim), np.float32)
        for srv, vals in results.items():
            full[(keys % n) == srv] = vals
        return full

    def push_sparse_grad(self, table: str, keys: np.ndarray,
                         grads: np.ndarray) -> None:
        keys = np.asarray(keys, np.int64).ravel()
        grads = np.asarray(grads, np.float32)
        n = len(self.endpoints)
        for srv in range(n):
            mask = (keys % n) == srv
            if not mask.any():
                continue
            self._call(srv, {"cmd": PUSH_SPARSE, "table": table,
                             "keys": keys[mask].tolist(),
                             "grad": grads[mask]}, site="ps.push")

    def push_sparse_delta(self, table: str, keys: np.ndarray,
                          deltas: np.ndarray) -> None:
        keys = np.asarray(keys, np.int64).ravel()
        deltas = np.asarray(deltas, np.float32)
        n = len(self.endpoints)
        for srv in range(n):
            mask = (keys % n) == srv
            if not mask.any():
                continue
            self._call(srv, {"cmd": PUSH_SPARSE_DELTA, "table": table,
                             "keys": keys[mask].tolist(),
                             "delta": deltas[mask]}, site="ps.push")

    # -- graph engine (reference: brpc client graph RPCs over
    #    common_graph_table.cc; nodes shard by id % n_servers) ---------

    def add_graph_node(self, table: str, ids, feats=None) -> None:
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self.endpoints)
        for srv in range(n):
            mask = (ids % n) == srv
            if not mask.any():
                continue
            msg = {"cmd": GRAPH_ADD_NODES, "table": table,
                   "ids": ids[mask].tolist()}
            if feats is not None:
                msg["feats"] = np.asarray(feats, np.float32)[mask]
            self._call(srv, msg)

    def add_graph_edges(self, table: str, srcs, dsts,
                        weights=None) -> None:
        srcs = np.asarray(srcs, np.int64).ravel()
        dsts = np.asarray(dsts, np.int64).ravel()
        n = len(self.endpoints)
        for srv in range(n):
            mask = (srcs % n) == srv  # edges live with their source node
            if not mask.any():
                continue
            msg = {"cmd": GRAPH_ADD_EDGES, "table": table,
                   "srcs": srcs[mask].tolist(),
                   "dsts": dsts[mask].tolist()}
            if weights is not None:
                msg["weights"] = np.asarray(
                    weights, np.float32)[mask].tolist()
            self._call(srv, msg)

    def remove_graph_node(self, table: str, ids) -> None:
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self.endpoints)
        for srv in range(n):
            mask = (ids % n) == srv
            if mask.any():
                self._call(srv, {"cmd": GRAPH_REMOVE_NODES,
                                 "table": table,
                                 "ids": ids[mask].tolist()})

    def sample_neighbors(self, table: str, ids, sample_size: int,
                         seed: int = 0):
        """Per-node weighted neighbor sample; server-side sampling, only
        sampled ids cross the wire. Returns ([len(ids), sample_size]
        int64 padded with -1, counts)."""
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self.endpoints)
        nbrs = np.full((ids.size, sample_size), -1, np.int64)
        cnt = np.zeros(ids.size, np.int32)
        for srv in range(n):
            mask = (ids % n) == srv
            if not mask.any():
                continue
            r = self._call(srv, {"cmd": GRAPH_SAMPLE_NEIGHBORS,
                                 "table": table,
                                 "ids": ids[mask].tolist(),
                                 "sample_size": sample_size,
                                 "seed": seed})
            nbrs[mask] = r["neighbors"]
            cnt[mask] = r["counts"]
        return nbrs, cnt

    def sample_graph_nodes(self, table: str, n_nodes: int,
                           seed: int = 0) -> np.ndarray:
        per = -(-n_nodes // len(self.endpoints))  # ceil: no remainder loss
        out = []
        for srv in range(len(self.endpoints)):
            r = self._call(srv, {"cmd": GRAPH_SAMPLE_NODES,
                                 "table": table, "n": per, "seed": seed})
            out.append(np.asarray(r["ids"], np.int64))
        return np.concatenate(out)[:n_nodes]

    def get_node_feat(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self.endpoints)
        out = None
        for srv in range(n):
            mask = (ids % n) == srv
            if not mask.any():
                continue
            r = self._call(srv, {"cmd": GRAPH_GET_FEAT, "table": table,
                                 "ids": ids[mask].tolist()})
            f = np.asarray(r["feats"], np.float32)
            if out is None:
                out = np.zeros((ids.size, f.shape[1]), np.float32)
            out[mask] = f
        return out if out is not None else np.zeros((ids.size, 0),
                                                    np.float32)

    def pull_graph_list(self, table: str, start: int, size: int):
        # global pagination: each server returns its first start+size
        # ids; the offset applies to the merged order (a per-server
        # offset would skip ids)
        out = []
        for srv in range(len(self.endpoints)):
            r = self._call(srv, {"cmd": GRAPH_LIST, "table": table,
                                 "start": 0, "size": start + size})
            out.extend(r["ids"])
        return sorted(out)[start:start + size]

    def barrier(self, world: int = 0) -> None:
        """world > 1: blocking rendezvous across that many trainers
        (sync-PS lockstep); otherwise the legacy counter ping."""
        for srv in range(len(self.endpoints)):
            self._call(srv, {"cmd": BARRIER, "world": world})

    def close(self) -> None:
        """Disconnect without stopping the servers (a trainer leaving a
        shared job)."""
        for s in self._socks:
            if s is not None:
                s.close()

    def stop(self) -> None:
        for srv in range(len(self.endpoints)):
            try:
                self._call(srv, {"cmd": STOP})
            except Exception:
                pass
        for s in self._socks:
            if s is not None:
                s.close()


class GeoCommunicator:
    """Geo-SGD for sparse tables (reference: GeoCommunicator in
    service/communicator.cc + sparse_geo_table.cc; strategy
    a_sync_configs k_steps / geo mode). Each trainer trains a LOCAL
    replica of touched embedding rows; every ``k_steps`` it ships the
    accumulated parameter DELTAS (not grads) to the PS and refreshes its
    replica — communication cost scales with touched rows, not steps."""

    def __init__(self, client: PSClient, table: str, emb_dim: int,
                 k_steps: int = 10, lr: float = 0.01,
                 max_local_rows: int = 1_000_000):
        self.client = client
        self.table = table
        self.emb_dim = emb_dim
        self.k_steps = max(1, int(k_steps))
        self.lr = lr
        self.max_local_rows = int(max_local_rows)
        self.local: Dict[int, np.ndarray] = {}  # insertion-ordered
        self.base: Dict[int, np.ndarray] = {}
        self._touched: set = set()
        self._t = 0

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Fetch rows, serving locally-trained replicas when present."""
        keys = np.asarray(keys, np.int64).ravel()
        missing = [int(k) for k in keys if int(k) not in self.local]
        if missing:
            rows = self.client.pull_sparse(self.table,
                                           np.asarray(missing, np.int64))
            for k, r in zip(missing, rows):
                self.local[k] = r.copy()
                self.base[k] = r.copy()
        for k in keys:  # re-insert = most recently used, so hot read
            k = int(k)   # rows survive the insertion-ordered eviction
            self.local[k] = self.local.pop(k)
        out = np.stack([self.local[int(k)] for k in keys])
        self._evict(protect=set(int(k) for k in keys))
        return out

    def _evict(self, protect: Optional[set] = None) -> None:
        """Bound the replica; never evict rows with unsynced deltas or
        rows the current call is about to use."""
        if len(self.local) <= self.max_local_rows:
            return
        keep = self._touched | (protect or set())
        for k in list(self.local):
            if len(self.local) <= self.max_local_rows:
                break
            if k in keep:
                continue
            del self.local[k]
            self.base.pop(k, None)

    def push_grad(self, keys: np.ndarray, grads: np.ndarray) -> None:
        """Local SGD on the replica; periodic delta sync."""
        keys = np.asarray(keys, np.int64).ravel()
        self.pull(keys)  # one batched fetch of any missing rows
        for k, g in zip(keys, np.asarray(grads, np.float32)):
            k = int(k)
            self.local[k] = self.local[k] - self.lr * g
            self._touched.add(k)
        self._t += 1
        if self._t % self.k_steps == 0:
            self.sync()

    def sync(self) -> None:
        if not self._touched:
            return
        keys = np.asarray(sorted(self._touched), np.int64)
        deltas = np.stack([self.local[int(k)] - self.base[int(k)]
                           for k in keys])
        self.client.push_sparse_delta(self.table, keys, deltas)
        # refresh replica with the server's merged view
        rows = self.client.pull_sparse(self.table, keys)
        for k, r in zip(keys, rows):
            k = int(k)
            self.local.pop(k, None)  # re-insert = most recently used
            self.local[k] = r.copy()
            self.base[k] = r.copy()
        self._touched.clear()
        # all deltas are synced now — eviction only costs a re-pull
        self._evict()


class AsyncCommunicator:
    """reference: service/communicator.cc — background thread draining a
    send queue of dense grads (async SGD mode; a_sync_configs)."""

    def __init__(self, client: PSClient, send_wait_s: float = 0.01,
                 max_queue: int = 64):
        self.client = client
        self._queue: List[Tuple[str, np.ndarray]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wait = send_wait_s
        self._max = max_queue
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push(self, table: str, grad: np.ndarray) -> None:
        with self._lock:
            if len(self._queue) >= self._max:
                # merge oldest grads per table (max_merge_var_num analog)
                self._flush_locked()
            self._queue.append((table, np.asarray(grad)))

    def _flush_locked(self) -> None:
        merged: Dict[str, np.ndarray] = {}
        for t, g in self._queue:
            merged[t] = merged.get(t, 0) + g
        self._queue.clear()
        for t, g in merged.items():
            self.client.push_dense_grad(t, g)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.flush()
            self._stop.wait(self._wait)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.flush()


class NativePSServer:
    """C++ PS service (native/pt_ps.cc): POSIX-socket transport, binary
    protocol, table math (dense SGD/Adam, sparse SGD/Adagrad, geo deltas)
    applied in C++ — the brpc_ps_server.cc equivalent. Same surface as
    PSServer for in-memory tables; SSD/sqlite tables stay on the Python
    server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from .. import native
        lib = native.get_lib()
        if lib is None or not hasattr(lib, "pt_ps_server_create"):
            raise RuntimeError("native PS transport unavailable "
                               "(toolchain missing?)")
        self._lib = lib
        self._h = lib.pt_ps_server_create()
        self.host = host
        self._port_req = port
        self._dense_sizes: Dict[str, Tuple[int, ...]] = {}
        self._started = False

    def add_dense_table(self, name: str, shape, optimizer: str = "sgd",
                        lr: float = 0.01, beta1=0.9, beta2=0.999,
                        eps=1e-8) -> None:
        shape = tuple(int(s) for s in np.atleast_1d(shape))
        size = int(np.prod(shape))
        self._dense_sizes[name] = shape
        self._lib.pt_ps_server_add_dense(
            self._h, name.encode(), size,
            1 if optimizer == "adam" else 0, lr, beta1, beta2, eps)

    def add_sparse_table(self, name: str, emb_dim: int, lr: float = 0.01,
                         initializer_std: float = 0.01,
                         optimizer: str = "adagrad", seed: int = 0) -> None:
        self._lib.pt_ps_server_add_sparse(
            self._h, name.encode(), int(emb_dim),
            1 if optimizer == "adagrad" else 0, lr, initializer_std,
            int(seed))

    def start(self) -> None:
        rc = self._lib.pt_ps_server_start(self._h, self.host.encode(),
                                          self._port_req)
        if rc != 0:
            raise RuntimeError("native PS server failed to bind")
        self.port = self._lib.pt_ps_server_port(self._h)
        self._started = True

    def dense_value(self, name: str) -> np.ndarray:
        import ctypes
        shape = self._dense_sizes[name]
        out = np.empty(int(np.prod(shape)), np.float32)
        rc = self._lib.pt_ps_server_dense_read(
            self._h, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size)
        if rc != 0:
            raise KeyError(name)
        return out.reshape(shape)

    def stop(self) -> None:
        if self._h:
            self._lib.pt_ps_server_stop(self._h)

    def __del__(self):
        try:
            if self._h:
                self._lib.pt_ps_server_destroy(self._h)
                self._h = None
        except Exception:
            pass

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"


class NativePSClient:
    """C++-transport client with the PSClient surface (sparse keys shard
    by key % n_servers; dense tables on a table-hash server) — works as a
    drop-in for GeoCommunicator/AsyncCommunicator."""

    def __init__(self, endpoints: Sequence[str]):
        import ctypes
        from .. import native
        lib = native.get_lib()
        if lib is None or not hasattr(lib, "pt_ps_connect"):
            raise RuntimeError("native PS transport unavailable")
        self._lib = lib
        self._ct = ctypes
        self.endpoints = list(endpoints)
        self._conns = []
        for ep in self.endpoints:
            host, _, port = ep.partition(":")
            c = lib.pt_ps_connect(host.encode(), int(port))
            if not c:
                raise ConnectionError(f"cannot connect to PS {ep}")
            self._conns.append(c)
        self._dims: Dict[str, int] = {}
        self._dense_sizes: Dict[str, int] = {}

    def _fp(self, arr: np.ndarray):
        return arr.ctypes.data_as(self._ct.POINTER(self._ct.c_float))

    def _kp(self, arr: np.ndarray):
        return arr.ctypes.data_as(self._ct.POINTER(self._ct.c_int64))

    def _dense_server(self, table: str) -> int:
        # stable across processes (built-in hash() is salted per process,
        # which would route the same table to different servers on
        # different trainers)
        import zlib
        return zlib.crc32(table.encode()) % len(self.endpoints)

    def _dim(self, table: str) -> int:
        d = self._dims.get(table)
        if d is None:
            d = int(self._lib.pt_ps_table_dim(self._conns[0],
                                              table.encode()))
            if d <= 0:
                raise KeyError(f"unknown sparse table {table!r}")
            self._dims[table] = d
        return d

    def push_dense_init(self, table: str, value: np.ndarray) -> None:
        v = np.ascontiguousarray(value, np.float32)
        self._dense_sizes[table] = v.size
        rc = self._lib.pt_ps_push_dense(
            self._conns[self._dense_server(table)], table.encode(),
            self._fp(v), v.size, 1)
        if rc != 0:
            raise RuntimeError(f"push_dense_init {table} failed")

    def push_dense_grad(self, table: str, grad: np.ndarray) -> None:
        g = np.ascontiguousarray(grad, np.float32)
        self._dense_sizes.setdefault(table, g.size)
        rc = self._lib.pt_ps_push_dense(
            self._conns[self._dense_server(table)], table.encode(),
            self._fp(g), g.size, 0)
        if rc != 0:
            raise RuntimeError(f"push_dense_grad {table} failed")

    def pull_dense(self, table: str, size: Optional[int] = None
                   ) -> np.ndarray:
        n = size or self._dense_sizes.get(table)
        if n is None:
            raise KeyError(f"dense table {table!r}: size unknown — pass "
                           "size= or push first")
        out = np.empty(int(n), np.float32)
        rc = self._lib.pt_ps_pull_dense(
            self._conns[self._dense_server(table)], table.encode(),
            self._fp(out), out.size)
        if rc != 0:
            raise RuntimeError(f"pull_dense {table} failed")
        return out

    def pull_sparse(self, table: str, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(np.asarray(keys, np.int64).ravel())
        n = len(self.endpoints)
        dim = self._dim(table)
        full = np.zeros((keys.size, dim), np.float32)
        for srv in range(n):
            mask = (keys % n) == srv
            if not mask.any():
                continue
            sub = np.ascontiguousarray(keys[mask])
            out = np.empty((sub.size, dim), np.float32)
            rc = self._lib.pt_ps_pull_sparse(
                self._conns[srv], table.encode(), self._kp(sub), sub.size,
                self._fp(out), dim)
            if rc != 0:
                raise RuntimeError(f"pull_sparse {table} failed")
            full[mask] = out
        return full

    def _push_sparse(self, table: str, keys, grads, delta: int) -> None:
        keys = np.ascontiguousarray(np.asarray(keys, np.int64).ravel())
        grads = np.ascontiguousarray(grads, np.float32)
        n = len(self.endpoints)
        dim = self._dim(table)
        for srv in range(n):
            mask = (keys % n) == srv
            if not mask.any():
                continue
            sub = np.ascontiguousarray(keys[mask])
            g = np.ascontiguousarray(grads[mask])
            rc = self._lib.pt_ps_push_sparse(
                self._conns[srv], table.encode(), self._kp(sub), sub.size,
                self._fp(g), dim, delta)
            if rc != 0:
                raise RuntimeError(f"push_sparse {table} failed")

    def push_sparse_grad(self, table, keys, grads) -> None:
        self._push_sparse(table, keys, grads, 0)

    def push_sparse_delta(self, table, keys, deltas) -> None:
        self._push_sparse(table, keys, deltas, 1)

    def sparse_size(self, table: str) -> int:
        return int(self._lib.pt_ps_sparse_size(self._conns[0],
                                               table.encode()))

    def barrier(self) -> None:
        for c in self._conns:
            self._lib.pt_ps_barrier(c)

    def close(self) -> None:
        """Disconnect without stopping the servers."""
        for c in self._conns:
            self._lib.pt_ps_disconnect(c)
        self._conns = []

    def stop(self) -> None:
        for c in self._conns:
            try:
                self._lib.pt_ps_stop_server(c)
            except Exception:
                pass
            self._lib.pt_ps_disconnect(c)
        self._conns = []


# --------------------------------------------------------------------------
# Graph engine table (reference: distributed/table/common_graph_table.cc —
# the GNN graph store: sharded node/edge storage, weighted neighbor
# sampling, node sampling, feature pull, served over the PS RPC).
# Nodes shard across servers by id % n_servers (the reference shards by
# id % shard_num); sampling RPCs run server-side so only the sampled
# ids/features cross the wire.
# --------------------------------------------------------------------------

GRAPH_ADD_NODES = "graph_add_nodes"
GRAPH_ADD_EDGES = "graph_add_edges"
GRAPH_REMOVE_NODES = "graph_remove_nodes"
GRAPH_SAMPLE_NEIGHBORS = "graph_sample_neighbors"
GRAPH_SAMPLE_NODES = "graph_sample_nodes"
GRAPH_GET_FEAT = "graph_get_feat"
GRAPH_LIST = "graph_list"


class GraphTable:
    """Server-side graph store (common_graph_table.cc capability)."""

    def __init__(self, feat_dim: int = 0):
        self.feat_dim = feat_dim
        self.nodes: Dict[int, np.ndarray] = {}
        self.edges: Dict[int, List[Tuple[int, float]]] = {}
        # thread-per-connection server: same locking discipline as the
        # other table kinds
        self._lock = threading.Lock()

    def add_nodes(self, ids, feats=None) -> None:
        with self._lock:
            for i, nid in enumerate(ids):
                nid = int(nid)
                if feats is not None:
                    self.nodes[nid] = np.asarray(feats[i], np.float32)
                else:
                    self.nodes.setdefault(
                        nid, np.zeros(self.feat_dim, np.float32))

    def add_edges(self, srcs, dsts, weights=None) -> None:
        with self._lock:
            for i, (s, d) in enumerate(zip(srcs, dsts)):
                w = float(weights[i]) if weights is not None else 1.0
                self.edges.setdefault(int(s), []).append((int(d), w))
                self.nodes.setdefault(
                    int(s), np.zeros(self.feat_dim, np.float32))

    def remove_nodes(self, ids) -> None:
        with self._lock:
            for nid in ids:
                self.nodes.pop(int(nid), None)
                self.edges.pop(int(nid), None)

    def sample_neighbors(self, ids, sample_size: int, seed: int = 0):
        """Weighted sampling without replacement per node (reference
        random_sample_neighboors); returns (neighbor ids padded with -1,
        actual counts). Zero/negative-weight edges are never sampled."""
        rng = np.random.default_rng(seed)
        out = np.full((len(ids), sample_size), -1, np.int64)
        cnt = np.zeros(len(ids), np.int32)
        with self._lock:
            for r, nid in enumerate(ids):
                nbrs = [e for e in self.edges.get(int(nid), [])
                        if e[1] > 0.0]
                if not nbrs:
                    continue
                k = min(sample_size, len(nbrs))
                w = np.asarray([x[1] for x in nbrs], np.float64)
                pick = rng.choice(len(nbrs), size=k, replace=False,
                                  p=w / w.sum())
                out[r, :k] = [nbrs[i][0] for i in pick]
                cnt[r] = k
        return out, cnt

    def sample_nodes(self, n: int, seed: int = 0):
        with self._lock:
            ids = np.asarray(sorted(self.nodes), np.int64)
        if not len(ids):
            return ids
        rng = np.random.default_rng(seed)
        return rng.choice(ids, size=min(n, len(ids)), replace=False)

    def get_feat(self, ids) -> np.ndarray:
        dim = self.feat_dim
        out = np.zeros((len(ids), dim), np.float32)
        with self._lock:
            for r, nid in enumerate(ids):
                f = self.nodes.get(int(nid))
                if f is not None and len(f):
                    out[r, :len(f)] = f[:dim]
        return out

    def node_list(self, start: int, size: int):
        with self._lock:
            ids = sorted(self.nodes)
        return ids[start:start + size]

    def load_edges(self, path: str, reversed_edge: bool = False) -> None:
        """reference load_edges: lines of 'src\\tdst[\\tweight]'."""
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) < 2:
                    continue
                s, d = int(parts[0]), int(parts[1])
                w = float(parts[2]) if len(parts) > 2 else 1.0
                if reversed_edge:
                    s, d = d, s
                self.add_edges([s], [d], [w])

    def load_nodes(self, path: str) -> None:
        """reference load_nodes: 'node_id feat0 feat1 ...' per line."""
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                nid = int(parts[0])
                feats = [np.asarray([float(v) for v in parts[1:]],
                                    np.float32)] if len(parts) > 1 else \
                    None
                self.add_nodes([nid], feats)
