"""Mesh topology for hybrid parallelism.

TPU-native equivalent of the reference's rank-mesh machinery
(reference: python/paddle/distributed/fleet/base/topology.py:35
CommunicateTopology — an N-d cartesian rank mesh, :116
HybridCommunicateGroup — one comm group per axis). Here the mesh is a
jax.sharding.Mesh whose named axes ride ICI; "comm group per axis" becomes
"collectives over a named mesh axis", and the reference's ring_id plumbing
disappears into GSPMD.

Axis naming convention (order matters for ICI locality: fastest-varying
last): ("pp", "dp", "sharding", "sep", "mp") — model parallel innermost so
its collectives ride the shortest ICI links, matching the reference's
hybrid order data>pipe>sharding>model (topology.py:57).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_HYBRID_AXES = ("pp", "dp", "sharding", "sep", "mp")


class CommunicateTopology:
    """N-d cartesian topology over ranks (device indices)."""

    def __init__(self, hybrid_group_names: Sequence[str] =
                 ("data", "pipe", "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate)
                if c[axis] == index]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for fixed in itertools.product(*(range(self._dims[i])
                                         for i in other)):
            group = []
            for v in range(self._dims[axis]):
                coord = list(fixed)
                coord.insert(axis, v)
                group.append(self._coord2rank[tuple(coord)])
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Builds the device mesh + per-axis views for dp/mp/pp/sharding/sep.

    Reference: topology.py:116 HybridCommunicateGroup (one NCCL group per
    axis per index) — here one jax Mesh; "groups" are just named axes.
    """

    def __init__(self, dp_degree: int = 1, mp_degree: int = 1,
                 pp_degree: int = 1, sharding_degree: int = 1,
                 sep_degree: int = 1, devices=None):
        devices = list(devices if devices is not None else jax.devices())
        need = dp_degree * mp_degree * pp_degree * sharding_degree * \
            sep_degree
        if need > len(devices):
            raise ValueError(
                f"hybrid degrees {need} exceed device count {len(devices)}")
        devices = devices[:need]
        self.dims = {"pp": pp_degree, "dp": dp_degree,
                     "sharding": sharding_degree, "sep": sep_degree,
                     "mp": mp_degree}
        shape = tuple(self.dims[a] for a in _HYBRID_AXES)
        dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, _HYBRID_AXES)
        self.topology = CommunicateTopology(
            ("pipe", "data", "sharding", "sep", "model"), shape)
        self.global_rank = 0  # SPMD: per-device coords live in the mesh
        self.nranks = need

    # -- reference-compatible accessors ---------------------------------------

    def get_parallel_mode(self) -> str:
        if self.dims["pp"] > 1:
            return "pipeline"
        if self.dims["sharding"] > 1:
            return "sharding_parallel"
        if self.dims["mp"] > 1:
            return "tensor_parallel"
        return "data_parallel"

    def get_data_parallel_world_size(self) -> int:
        return self.dims["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self.dims["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.dims["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self.dims["sharding"]

    def get_sep_parallel_world_size(self) -> int:
        return self.dims["sep"]

    # axis names for collectives inside shard_map/pjit
    def get_data_parallel_group(self) -> str:
        return "dp"

    def get_model_parallel_group(self) -> str:
        return "mp"

    def get_pipe_parallel_group(self) -> str:
        return "pp"

    def get_sharding_parallel_group(self) -> str:
        return "sharding"

    def get_sep_parallel_group(self) -> str:
        return "sep"

    def get_check_parallel_group(self) -> str:
        return "mp"

    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))


_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup) -> None:
    global _HCG
    _HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG


def create_hybrid_communicate_group(dp_degree=1, mp_degree=1, pp_degree=1,
                                    sharding_degree=1, sep_degree=1,
                                    devices=None) -> HybridCommunicateGroup:
    hcg = HybridCommunicateGroup(dp_degree, mp_degree, pp_degree,
                                 sharding_degree, sep_degree, devices)
    set_hybrid_communicate_group(hcg)
    return hcg


def make_mesh(axis_shapes: Dict[str, int], devices=None) -> Mesh:
    """Generic mesh builder for custom axis layouts."""
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axis_shapes)
    shape = tuple(axis_shapes[n] for n in names)
    need = int(np.prod(shape))
    dev_array = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev_array, names)
