"""Mesh topology for hybrid parallelism.

TPU-native equivalent of the reference's rank-mesh machinery
(reference: python/paddle/distributed/fleet/base/topology.py:35
CommunicateTopology — an N-d cartesian rank mesh, :116
HybridCommunicateGroup — one comm group per axis). Here the mesh is a
jax.sharding.Mesh whose named axes ride ICI; "comm group per axis" becomes
"collectives over a named mesh axis", and the reference's ring_id plumbing
disappears into GSPMD.

Axis naming convention (order matters for ICI locality: fastest-varying
last): ("pp", "dp", "sharding", "sep", "mp") — model parallel innermost so
its collectives ride the shortest ICI links, matching the reference's
hybrid order data>pipe>sharding>model (topology.py:57).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_HYBRID_AXES = ("pp", "dp", "sharding", "sep", "mp")


def build_device_array(shape: Tuple[int, ...], devices=None,
                       topology_aware: Optional[bool] = None):
    """Topology-aware device placement for a mesh of ``shape``.

    The reference hand-tunes NCCL ring order for its hybrid groups
    (platform/nccl_helper.h:190, sharding_optimizer.py:968); the TPU
    analog is laying mesh axes onto the physical ICI torus. A naive
    ``reshape(jax.devices())`` keeps enumeration order, which on a real
    torus (e.g. v4-64) can put the innermost (mp) axis on non-adjacent
    chips. ``mesh_utils.create_device_mesh`` solves the assignment so
    later axes land on the tightest physical loops; on multi-slice
    deployments ``create_hybrid_device_mesh`` puts the leading axes
    (pp/dp) on DCN and the rest on ICI.

    Returns (device_array, assignment_tag) where the tag records which
    strategy was used: "hybrid_dcn", "topology_aware", or
    "enumeration_order" (explicit devices= / non-TPU fallback).

    ``topology_aware`` overrides the default policy (None = solve the
    assignment only when the caller did not fix an explicit device
    order): True forces the solver on an explicit TPU device list (the
    AOT scale proof passes compile-only topology devices), False forces
    plain reshape.
    """
    import math

    explicit = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    need = int(np.prod(shape))
    devices = devices[:need]
    if topology_aware is None:
        topology_aware = not explicit
    if not topology_aware or devices[-1].platform != "tpu":
        # Explicit order is the caller's contract; non-TPU (the virtual
        # CPU test mesh) has no physical topology to exploit.
        return np.asarray(devices).reshape(shape), "enumeration_order"

    from jax.experimental import mesh_utils

    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    n_slices = len(slice_ids)
    if n_slices > 1:
        # Factor the slice count onto the leading (outermost) axes —
        # those are dp/pp in the hybrid order, whose collectives
        # tolerate DCN latency; mp/sep stay intra-slice on ICI.
        dcn = [1] * len(shape)
        remaining = n_slices
        for i, dim in enumerate(shape):
            f = math.gcd(dim, remaining)
            dcn[i] = f
            remaining //= f
            if remaining == 1:
                break
        if remaining == 1:
            try:
                arr = mesh_utils.create_hybrid_device_mesh(
                    tuple(s // d for s, d in zip(shape, dcn)), tuple(dcn),
                    devices=devices)
                return arr, "hybrid_dcn"
            except (ValueError, AssertionError, NotImplementedError):
                pass
    try:
        arr = mesh_utils.create_device_mesh(shape, devices=devices)
        return arr, "topology_aware"
    except (ValueError, AssertionError, NotImplementedError):
        pass
    arr = _solve_per_core_mesh(shape, devices)
    if arr is not None:
        return arr, "topology_aware"
    return np.asarray(devices).reshape(shape), "enumeration_order"


def _solve_per_core_mesh(shape: Tuple[int, ...], devices):
    """create_device_mesh refuses per-TensorCore v4+ device lists (it
    wants megacore, one device per chip) — but compile-only topologies
    (jax.experimental.topologies) expose 2 cores/chip. Solve the
    assignment at CHIP level with one representative core per chip, then
    expand each chip into its cores along the innermost axis, so sibling
    cores are always mp-neighbors (hop 0) and the chip-level solve fixes
    the ICI layout. Returns None when the structure doesn't apply."""
    from collections import defaultdict

    from jax.experimental import mesh_utils

    by_chip = defaultdict(list)
    for d in devices:
        coords = getattr(d, "coords", None)
        if coords is None:
            return None
        by_chip[tuple(coords)].append(d)
    counts = {len(v) for v in by_chip.values()}
    if len(counts) != 1:
        return None
    cpc = counts.pop()
    if cpc == 1 or shape[-1] % cpc != 0:
        return None
    for chip in by_chip.values():
        chip.sort(key=lambda d: getattr(d, "core_on_chip", d.id))
    chip_shape = shape[:-1] + (shape[-1] // cpc,)
    reps = [chip[0] for chip in by_chip.values()]
    try:
        chip_mesh = mesh_utils.create_device_mesh(chip_shape, devices=reps)
    except (ValueError, AssertionError, NotImplementedError):
        return None
    out = np.empty(shape, dtype=object)
    flat_out = out.reshape(-1, shape[-1])
    flat_chip = chip_mesh.reshape(-1, chip_shape[-1])
    for row in range(flat_out.shape[0]):
        for j in range(chip_shape[-1]):
            cores = by_chip[tuple(flat_chip[row, j].coords)]
            for k in range(cpc):
                flat_out[row, j * cpc + k] = cores[k]
    return out


def mesh_axis_locality(dev_array: "np.ndarray", axis_names=None) -> Dict:
    """Physical ICI locality per mesh axis: mean/max chip-torus hop
    between consecutive devices along each axis (wrap link included for
    rings longer than 2). Two TensorCores of one chip are hop 0. Returns
    {} when devices carry no coords (CPU/virtual meshes)."""
    devs = dev_array.ravel()
    if not hasattr(devs[0], "coords") or devs[0].coords is None:
        return {}
    coords = np.asarray([d.coords for d in devs]).reshape(
        dev_array.shape + (-1,))
    bounds = coords.reshape(-1, coords.shape[-1]).max(axis=0) + 1

    def hop(a, b, wrap_ok):
        # Torus wraparound credit only in dimensions the LINE actually
        # spans end-to-end: a mesh axis laid along a sub-block of a
        # wider physical ring has no wrap link of its own, and counting
        # one would understate the distance (and let the scale proof's
        # max-hop assertion pass for a non-adjacent placement).
        d = np.abs(a - b)
        wrapped = np.where(wrap_ok, np.minimum(d, bounds - d), d)
        return int(wrapped.sum())

    names = axis_names or [f"axis{i}" for i in range(dev_array.ndim)]
    out = {}
    for ax, name in enumerate(names):
        n = dev_array.shape[ax]
        if n == 1:
            continue
        lines = np.moveaxis(coords, ax, 0).reshape(n, -1, coords.shape[-1])
        hops = []
        for line_idx in range(lines.shape[1]):
            line = lines[:, line_idx]
            wrap_ok = np.array([
                len(set(line[:, dim])) == bounds[dim]
                for dim in range(line.shape[1])])
            pairs = [(i, i + 1) for i in range(n - 1)]
            if n > 2:
                pairs.append((n - 1, 0))  # ring wrap link
            hops.extend(hop(line[i], line[j], wrap_ok)
                        for i, j in pairs)
        out[name] = {"mean_hop": round(float(np.mean(hops)), 3),
                     "max_hop": int(np.max(hops)), "size": n}
    return out


class CommunicateTopology:
    """N-d cartesian topology over ranks (device indices)."""

    def __init__(self, hybrid_group_names: Sequence[str] =
                 ("data", "pipe", "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate)
                if c[axis] == index]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for fixed in itertools.product(*(range(self._dims[i])
                                         for i in other)):
            group = []
            for v in range(self._dims[axis]):
                coord = list(fixed)
                coord.insert(axis, v)
                group.append(self._coord2rank[tuple(coord)])
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Builds the device mesh + per-axis views for dp/mp/pp/sharding/sep.

    Reference: topology.py:116 HybridCommunicateGroup (one NCCL group per
    axis per index) — here one jax Mesh; "groups" are just named axes.
    """

    def __init__(self, dp_degree: int = 1, mp_degree: int = 1,
                 pp_degree: int = 1, sharding_degree: int = 1,
                 sep_degree: int = 1, devices=None,
                 topology_aware: Optional[bool] = None):
        avail = list(devices) if devices is not None else jax.devices()
        need = dp_degree * mp_degree * pp_degree * sharding_degree * \
            sep_degree
        if need > len(avail):
            raise ValueError(
                f"hybrid degrees {need} exceed device count {len(avail)}")
        self.dims = {"pp": pp_degree, "dp": dp_degree,
                     "sharding": sharding_degree, "sep": sep_degree,
                     "mp": mp_degree}
        shape = tuple(self.dims[a] for a in _HYBRID_AXES)
        dev_array, self.mesh_assignment = build_device_array(
            shape, avail if devices is not None else None, topology_aware)
        self.mesh = Mesh(dev_array, _HYBRID_AXES)
        self.topology = CommunicateTopology(
            ("pipe", "data", "sharding", "sep", "model"), shape)
        self.global_rank = 0  # SPMD: per-device coords live in the mesh
        self.nranks = need

    # -- reference-compatible accessors ---------------------------------------

    def get_parallel_mode(self) -> str:
        if self.dims["pp"] > 1:
            return "pipeline"
        if self.dims["sharding"] > 1:
            return "sharding_parallel"
        if self.dims["mp"] > 1:
            return "tensor_parallel"
        return "data_parallel"

    def get_data_parallel_world_size(self) -> int:
        return self.dims["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self.dims["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.dims["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self.dims["sharding"]

    def get_sep_parallel_world_size(self) -> int:
        return self.dims["sep"]

    # axis names for collectives inside shard_map/pjit
    def get_data_parallel_group(self) -> str:
        return "dp"

    def get_model_parallel_group(self) -> str:
        return "mp"

    def get_pipe_parallel_group(self) -> str:
        return "pp"

    def get_sharding_parallel_group(self) -> str:
        return "sharding"

    def get_sep_parallel_group(self) -> str:
        return "sep"

    def get_check_parallel_group(self) -> str:
        return "mp"

    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))


_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup) -> None:
    global _HCG
    _HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG


def create_hybrid_communicate_group(dp_degree=1, mp_degree=1, pp_degree=1,
                                    sharding_degree=1, sep_degree=1,
                                    devices=None) -> HybridCommunicateGroup:
    hcg = HybridCommunicateGroup(dp_degree, mp_degree, pp_degree,
                                 sharding_degree, sep_degree, devices)
    set_hybrid_communicate_group(hcg)
    return hcg


def make_mesh(axis_shapes: Dict[str, int], devices=None) -> Mesh:
    """Generic mesh builder for custom axis layouts (topology-aware when
    the caller does not fix an explicit device order)."""
    names = tuple(axis_shapes)
    shape = tuple(axis_shapes[n] for n in names)
    dev_array, _ = build_device_array(shape, devices)
    return Mesh(dev_array, names)


# The serving mesh's user-facing "model" axis IS the fleet's mp axis:
# naming it "mp" lets the GPT weight PartitionSpecs that mp_layers.py
# already annotates (P(None, "mp") column, P("mp", None) row/vocab)
# apply to the decode engine verbatim — one pspec convention for
# training and serving instead of a parallel serving-only one.
SERVING_MODEL_AXIS = "mp"


def make_serving_mesh(model_parallel: int, devices=None) -> Mesh:
    """1-D tensor-parallel mesh for the decode engine / serving stack:
    ``model_parallel`` devices along the :data:`SERVING_MODEL_AXIS`
    axis. The same ``make_mesh`` path the fleet side uses, so a
    deployment that trains on an mp mesh serves on the identical
    layout (topology-aware placement included). ``model_parallel=1``
    is the graceful-degradation mesh: every sharding it produces is
    replicated, and engine outputs match the mesh-less path."""
    mp = int(model_parallel)
    if mp < 1:
        raise ValueError(f"model_parallel must be >= 1, got {mp}")
    avail = len(devices) if devices is not None else len(jax.devices())
    if mp > avail:
        raise ValueError(
            f"serving mesh model={mp} exceeds device count {avail}")
    return make_mesh({SERVING_MODEL_AXIS: mp}, devices=devices)


def parse_mesh_spec(spec) -> int:
    """Parse the serving CLI's ``--mesh`` value to a model-parallel
    degree: ``"model=N"`` (the documented form), ``"mp=N"`` (the
    underlying axis name), or a bare ``"N"``. Raises ValueError on
    anything else — the CLI surfaces it as a typed argument error, not
    a confusing mesh-construction failure later."""
    s = str(spec).strip()
    if "=" in s:
        key, _, val = s.partition("=")
        if key.strip() not in ("model", SERVING_MODEL_AXIS):
            raise ValueError(
                f"--mesh axis must be 'model' (or "
                f"{SERVING_MODEL_AXIS!r}), got {key.strip()!r}")
        s = val.strip()
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"--mesh expects 'model=N' or a bare integer, got {spec!r}")
    if n < 1:
        raise ValueError(f"--mesh model={n} must be >= 1")
    return n


def filter_pspec(pspec, mesh: Mesh) -> PartitionSpec:
    """Project a PartitionSpec onto ``mesh``: axis names the mesh does
    not carry are dropped (that dimension replicates). The hybrid-mesh
    pspecs name up to five axes (dp/mp/pp/sharding/sep); a serving
    mesh carries only ``mp``, and a weight annotated P(None, "mp")
    must mean "shard on mp, ignore the rest" there rather than fail."""
    if pspec is None:
        return PartitionSpec()
    axes = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in axes)
            if not kept:
                return None
            return kept if len(kept) > 1 else kept[0]
        return entry if entry in axes else None

    return PartitionSpec(*(keep(e) for e in tuple(pspec)))
