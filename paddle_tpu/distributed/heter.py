"""Heter-lite: host-resident embedding tables feeding a jitted TPU step.

The useful kernel of the reference's heter-PS/BoxPS stack
(service/heter_client.cc:1, framework/fleet/heter_ps/hashtable.h:1):
an embedding table too large for accelerator HBM lives in host memory
(or a PS), the dense math runs on-device, and only the looked-up rows
cross the host<->device boundary each step.

TPU-native wiring: the jitted train step pulls rows with
``jax.pure_callback`` (a custom_vjp forward) and pushes gradient rows
back with ``jax.experimental.io_callback`` (the backward): the table
never appears among the program's device buffers, so HBM holds O(batch)
rows instead of O(vocab). The host side applies the sparse optimizer
row-wise (SGD exactly matches a dense on-device SGD step, duplicates
included; adagrad matches the PS server's per-row rule). ``prefetch()``
warms a host cache on a background thread so the pull callback overlaps
the previous step's device compute (the heter-PS pipeline pattern);
pushes PATCH overlapping cached rows, so prefetched rows are never
stale relative to completed pushes.

Consistency model: the gradient push is an asynchronous effect —
fetching the step's loss does NOT await it, so a back-to-back next step
may pull rows from before the previous push lands (one-step bounded
staleness: exactly the reference's async-PS/geo training semantics,
communicator.cc AsyncCommunicator). For strict read-after-write — e.g.
loss-parity testing against an in-HBM baseline — call
``jax.effects_barrier()`` between steps.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor


class DenseHostTable:
    """Contiguous host-RAM embedding store with row-sparse updates.

    update="sgd": w[k] -= lr * g (sequential over duplicates — identical
    to a dense device SGD step on the summed gradient).
    update="adagrad": per-row accumulator, the common_sparse_table.cc
    server rule."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 lr: float = 0.1, update: str = "sgd",
                 initializer_std: float = 0.02, seed: int = 0):
        assert update in ("sgd", "adagrad"), update
        rng = np.random.default_rng(seed)
        self.weight = (rng.standard_normal(
            (num_embeddings, embedding_dim)) * initializer_std
        ).astype(np.float32)
        self.lr = lr
        self.update = update
        self._accum: Optional[np.ndarray] = None
        self._lock = threading.Lock()

    @property
    def nbytes(self) -> int:
        return self.weight.nbytes

    def pull(self, ids: np.ndarray) -> np.ndarray:
        with self._lock:
            return self.weight[np.asarray(ids, np.int64)]

    def push_grad(self, ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(len(ids), -1)
        with self._lock:
            if self.update == "adagrad":
                if self._accum is None:
                    self._accum = np.zeros_like(self.weight)
                np.add.at(self._accum, ids, g * g)
                denom = np.sqrt(self._accum[ids]) + 1e-6
                np.subtract.at(self.weight, ids, self.lr * g / denom)
            else:
                np.subtract.at(self.weight, ids, self.lr * g)


class HostEmbedding(Layer):
    """Embedding whose table lives on the HOST; drop-in for nn.Embedding
    inside any jitted step (TrainStep / fleet.distributed_jit).

    The table is NOT a Parameter: the device optimizer never sees it;
    its rows update host-side in the backward push. ``table`` may be a
    DenseHostTable or any object with pull(ids)/push_grad(ids, grads)
    (e.g. distributed.ps.SparseTable — the PS-backed variant)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 lr: float = 0.1, update: str = "sgd", table=None,
                 seed: int = 0):
        super().__init__()
        self.table = table if table is not None else DenseHostTable(
            num_embeddings, embedding_dim, lr=lr, update=update,
            seed=seed)
        self._dim = embedding_dim
        self._cache: Dict[bytes, np.ndarray] = {}
        self._prefetch_threads: Dict[bytes, threading.Thread] = {}
        # A zero-valued scalar Parameter threaded through the lookup's
        # custom_vjp. Without it autodiff PRUNES the lookup's backward
        # (its only real input is integer ids, so no differentiable path
        # reaches it) and the gradient push would silently never fire.
        # Its own gradient is defined as zero, so the device optimizer
        # never moves it.
        from ..nn.initializer import Constant
        self.anchor = self.create_parameter(
            (1,), default_initializer=Constant(0.0))

        dim = embedding_dim
        table_ref = self.table
        cache = self._cache
        threads = self._prefetch_threads
        # One lock makes prefetch fills and gradient pushes atomic with
        # respect to each other: a push PATCHES any already-cached rows
        # it just updated, and a fill that starts after a push reads the
        # fresh table — so prefetched rows are never stale even though
        # the fill overlaps the previous step's backward.
        coherence = threading.Lock()
        self._coherence = coherence

        def host_pull(ids: np.ndarray) -> np.ndarray:
            # READ-ONLY under jax.pure_callback's contract: XLA may
            # elide, cache, or re-execute this callback, so it must be
            # idempotent. Joining a finished thread is a no-op and the
            # cache is only peeked (eviction happens in prefetch()); a
            # replay between pushes returns identical rows. Do NOT wrap
            # a HostEmbedding forward in jax.checkpoint/remat — a replay
            # AFTER the backward's push would read post-update rows that
            # diverge from the saved forward activations.
            key = np.asarray(ids).tobytes()
            t = threads.get(key)
            if t is not None:
                t.join()
            with coherence:
                hit = cache.get(key)
                if hit is not None:
                    return hit[1]
                return table_ref.pull(
                    np.asarray(ids).reshape(-1)).reshape(
                        ids.shape + (dim,)).astype(np.float32)

        def host_push(ids: np.ndarray, grads: np.ndarray) -> None:
            flat = np.asarray(ids).reshape(-1)
            with coherence:
                table_ref.push_grad(flat, np.asarray(grads))
                pushed = np.unique(flat)
                for key, (cached_ids, rows) in list(cache.items()):
                    mask = np.isin(cached_ids.reshape(-1), pushed)
                    if mask.any():
                        fresh = table_ref.pull(
                            cached_ids.reshape(-1)[mask])
                        rows.reshape(-1, dim)[mask] = fresh

        @jax.custom_vjp
        def lookup(ids, anchor):
            del anchor  # differentiability anchor only
            return jax.pure_callback(
                host_pull,
                jax.ShapeDtypeStruct(tuple(ids.shape) + (dim,),
                                     jnp.float32),
                ids, vmap_method="sequential")

        def lookup_fwd(ids, anchor):
            return lookup(ids, anchor), (ids, anchor)

        def lookup_bwd(res, g):
            ids, anchor = res
            from jax.experimental import io_callback
            io_callback(host_push, None, ids, g, ordered=True)
            # anchor cotangent must match the anchor's aval — it may be
            # bf16 after model.to(dtype="bfloat16")
            return (np.zeros(ids.shape, jax.dtypes.float0),
                    jnp.zeros_like(anchor))

        lookup.defvjp(lookup_fwd, lookup_bwd)
        self._lookup = lookup

    def prefetch(self, ids) -> None:
        """Warm the pull cache on a background thread (overlaps the
        current step's device compute — call before the step that will
        consume ``ids``)."""
        ids = np.asarray(ids)
        key = ids.tobytes()
        if key in self._cache or key in self._prefetch_threads:
            return
        dim = self._dim
        # The pull path is read-only (pure_callback purity), so ALL
        # eviction lives here: drop finished prefetch threads and bound
        # the peek cache FIFO-style.
        with self._coherence:
            for k in list(self._prefetch_threads):
                t_old = self._prefetch_threads[k]
                if not t_old.is_alive():
                    self._prefetch_threads.pop(k, None)
            while len(self._cache) > 8:
                self._cache.pop(next(iter(self._cache)))

        def work():
            with self._coherence:
                rows = self.table.pull(ids.reshape(-1)).reshape(
                    ids.shape + (dim,)).astype(np.float32)
                self._cache[key] = (ids, rows)

        t = threading.Thread(target=work, daemon=True)
        self._prefetch_threads[key] = t
        t.start()

    def forward(self, x):
        from .. import dispatch
        ids = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        out = dispatch.call_fn(self._lookup, "host_embedding", True,
                               (ids, self.anchor), {})
        return out if isinstance(out, Tensor) else Tensor(out)
