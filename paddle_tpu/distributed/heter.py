"""Heter-lite: host-resident embedding tables feeding a jitted TPU step.

The useful kernel of the reference's heter-PS/BoxPS stack
(service/heter_client.cc:1, framework/fleet/heter_ps/hashtable.h:1):
an embedding table too large for accelerator HBM lives in host memory
(or a PS), the dense math runs on-device, and only the looked-up rows
cross the host<->device boundary each step.

TPU-native wiring: the jitted train step pulls rows with
``jax.pure_callback`` (a custom_vjp forward) and pushes gradient rows
back with ``jax.experimental.io_callback`` (the backward): the table
never appears among the program's device buffers, so HBM holds O(batch)
rows instead of O(vocab). The host side applies the sparse optimizer
row-wise (SGD exactly matches a dense on-device SGD step, duplicates
included; adagrad matches the PS server's per-row rule). ``prefetch()``
warms a host cache on a background thread so the pull callback overlaps
the previous step's device compute (the heter-PS pipeline pattern);
pushes PATCH overlapping cached rows, so prefetched rows are never
stale relative to completed pushes.

Consistency model: the gradient push is an asynchronous effect —
fetching the step's loss does NOT await it, so a back-to-back next step
may pull rows from before the previous push lands (one-step bounded
staleness: exactly the reference's async-PS/geo training semantics,
communicator.cc AsyncCommunicator). For strict read-after-write — e.g.
loss-parity testing against an in-HBM baseline — call
``jax.effects_barrier()`` between steps.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor


class DenseHostTable:
    """Contiguous host-RAM embedding store with row-sparse updates.

    update="sgd": w[k] -= lr * g (sequential over duplicates — identical
    to a dense device SGD step on the summed gradient).
    update="adagrad": per-row accumulator, the common_sparse_table.cc
    server rule."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 lr: float = 0.1, update: str = "sgd",
                 initializer_std: float = 0.02, seed: int = 0):
        assert update in ("sgd", "adagrad"), update
        rng = np.random.default_rng(seed)
        self.weight = (rng.standard_normal(
            (num_embeddings, embedding_dim)) * initializer_std
        ).astype(np.float32)
        self.lr = lr
        self.update = update
        self._accum: Optional[np.ndarray] = None
        self._lock = threading.Lock()

    @property
    def nbytes(self) -> int:
        return self.weight.nbytes

    def pull(self, ids: np.ndarray) -> np.ndarray:
        with self._lock:
            return self.weight[np.asarray(ids, np.int64)]

    def push_grad(self, ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(len(ids), -1)
        with self._lock:
            if self.update == "adagrad":
                if self._accum is None:
                    self._accum = np.zeros_like(self.weight)
                np.add.at(self._accum, ids, g * g)
                denom = np.sqrt(self._accum[ids]) + 1e-6
                np.subtract.at(self.weight, ids, self.lr * g / denom)
            else:
                np.subtract.at(self.weight, ids, self.lr * g)


class HostEmbedding(Layer):
    """Embedding whose table lives on the HOST; drop-in for nn.Embedding
    inside any jitted step (TrainStep / fleet.distributed_jit).

    The table is NOT a Parameter: the device optimizer never sees it;
    its rows update host-side in the backward push. ``table`` may be a
    DenseHostTable or any object with pull(ids)/push_grad(ids, grads)
    (e.g. distributed.ps.SparseTable — the PS-backed variant)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 lr: float = 0.1, update: str = "sgd", table=None,
                 seed: int = 0):
        super().__init__()
        self.table = table if table is not None else DenseHostTable(
            num_embeddings, embedding_dim, lr=lr, update=update,
            seed=seed)
        self._dim = embedding_dim
        self._cache: Dict[bytes, np.ndarray] = {}
        self._prefetch_threads: Dict[bytes, threading.Thread] = {}
        # A zero-valued scalar Parameter threaded through the lookup's
        # custom_vjp. Without it autodiff PRUNES the lookup's backward
        # (its only real input is integer ids, so no differentiable path
        # reaches it) and the gradient push would silently never fire.
        # Its own gradient is defined as zero, so the device optimizer
        # never moves it.
        from ..nn.initializer import Constant
        self.anchor = self.create_parameter(
            (1,), default_initializer=Constant(0.0))

        dim = embedding_dim
        table_ref = self.table
        cache = self._cache
        threads = self._prefetch_threads
        # One lock makes prefetch fills and gradient pushes atomic with
        # respect to each other: a push PATCHES any already-cached rows
        # it just updated, and a fill that starts after a push reads the
        # fresh table — so prefetched rows are never stale even though
        # the fill overlaps the previous step's backward.
        coherence = threading.Lock()
        self._coherence = coherence

        def host_pull(ids: np.ndarray) -> np.ndarray:
            # READ-ONLY under jax.pure_callback's contract: XLA may
            # elide, cache, or re-execute this callback, so it must be
            # idempotent. Joining a finished thread is a no-op and the
            # cache is only peeked (eviction happens in prefetch()); a
            # replay between pushes returns identical rows. Do NOT wrap
            # a HostEmbedding forward in jax.checkpoint/remat — a replay
            # AFTER the backward's push would read post-update rows that
            # diverge from the saved forward activations.
            key = np.asarray(ids).tobytes()
            t = threads.get(key)
            if t is not None:
                t.join()
            with coherence:
                hit = cache.get(key)
                if hit is not None:
                    return hit[1]
                return table_ref.pull(
                    np.asarray(ids).reshape(-1)).reshape(
                        ids.shape + (dim,)).astype(np.float32)

        def host_push(ids: np.ndarray, grads: np.ndarray) -> None:
            flat = np.asarray(ids).reshape(-1)
            with coherence:
                table_ref.push_grad(flat, np.asarray(grads))
                pushed = np.unique(flat)
                for key, (cached_ids, rows) in list(cache.items()):
                    mask = np.isin(cached_ids.reshape(-1), pushed)
                    if mask.any():
                        fresh = table_ref.pull(
                            cached_ids.reshape(-1)[mask])
                        rows.reshape(-1, dim)[mask] = fresh

        @jax.custom_vjp
        def lookup(ids, anchor):
            del anchor  # differentiability anchor only
            return jax.pure_callback(
                host_pull,
                jax.ShapeDtypeStruct(tuple(ids.shape) + (dim,),
                                     jnp.float32),
                ids, vmap_method="sequential")

        def lookup_fwd(ids, anchor):
            return lookup(ids, anchor), (ids, anchor)

        def lookup_bwd(res, g):
            ids, anchor = res
            from jax.experimental import io_callback
            io_callback(host_push, None, ids, g, ordered=True)
            # anchor cotangent must match the anchor's aval — it may be
            # bf16 after model.to(dtype="bfloat16")
            return (np.zeros(ids.shape, jax.dtypes.float0),
                    jnp.zeros_like(anchor))

        lookup.defvjp(lookup_fwd, lookup_bwd)
        self._lookup = lookup

    def prefetch(self, ids) -> None:
        """Warm the pull cache on a background thread (overlaps the
        current step's device compute — call before the step that will
        consume ``ids``)."""
        ids = np.asarray(ids)
        key = ids.tobytes()
        if key in self._cache or key in self._prefetch_threads:
            return
        dim = self._dim
        # The pull path is read-only (pure_callback purity), so ALL
        # eviction lives here: drop finished prefetch threads and bound
        # the peek cache FIFO-style.
        with self._coherence:
            for k in list(self._prefetch_threads):
                t_old = self._prefetch_threads[k]
                if not t_old.is_alive():
                    self._prefetch_threads.pop(k, None)
            while len(self._cache) > 8:
                self._cache.pop(next(iter(self._cache)))

        def work():
            with self._coherence:
                rows = self.table.pull(ids.reshape(-1)).reshape(
                    ids.shape + (dim,)).astype(np.float32)
                self._cache[key] = (ids, rows)

        t = threading.Thread(target=work, daemon=True)
        self._prefetch_threads[key] = t
        t.start()

    def forward(self, x):
        from .. import dispatch
        ids = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        out = dispatch.call_fn(self._lookup, "host_embedding", True,
                               (ids, self.anchor), {})
        return out if isinstance(out, Tensor) else Tensor(out)


class HeterPipelineTrainer:
    """Split-brain heterogeneous training — the reference heter-PS
    ORCHESTRATION (not just its table), TPU-native.

    Reference: the CPU-side trainer runs the sparse stage against the
    PS while the accelerator runs the dense net, exchanging only stage
    activations (distributed/service/heter_client.cc:1 SendAndRecvAsync;
    framework/fleet/heter_ps/hashtable.h:1 pull/push;
    framework/fleet/box_wrapper.cc:1 BoxPS ads pipeline). Here:

    - a CPU WORKER POOL (ThreadPoolExecutor) runs the sparse stage:
      embedding pulls + per-slot layout forward, gradient scatter +
      table push backward — against any pull/push_grad table
      (DenseHostTable, distributed.ps.SparseTable over the socket PS,
      or the native C++ server via ps.NativePSClient wrappers);
    - the TPU runs ONE jitted dense stage: fwd + bwd + optimizer
      update, returning the activation cotangent that feeds the CPU
      backward;
    - the stages PIPELINE: batch i+1's sparse forward is submitted to
      the pool as soon as batch i's device step is dispatched (jax
      dispatch is async), and sparse backwards drain on the pool —
      the heter_section_worker microbatch overlap.

    The sparse stage layout is the CTR convention: ids [B, n_slots] ->
    rows [B, n_slots, dim] -> concat [B, n_slots*dim] feeding the dense
    model; its backward is an exact reshape-scatter (no pooling
    approximation), so training matches a monolithic model with the
    same update rules (tests/test_heter_embedding.py parity)."""

    def __init__(self, table, embedding_dim: int, dense_model,
                 optimizer, loss_fn, pool_workers: int = 2):
        import jax.numpy as jnp

        from ..jit import functional_state
        from ..nn.layer import bind_state

        self.table = table
        self.dim = embedding_dim
        self.model = dense_model
        self.optimizer = optimizer
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(max_workers=pool_workers)
        state = functional_state(dense_model)
        self._params = state["params"]
        self._buffers = state["buffers"]
        self._opt_state = optimizer.init(self._params)

        def device_step(params, opt_state, acts, labels, lr):
            def loss_of(p, a):
                with bind_state(dense_model,
                                {"params": p, "buffers": self._buffers}):
                    return loss_fn(dense_model, Tensor(a),
                                   Tensor(labels)).value
            loss, (gp, ga) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(params, acts)
            new_p, new_s = optimizer.apply_gradients(params, gp,
                                                     opt_state, lr)
            return new_p, new_s, loss, ga

        self._device_step = jax.jit(device_step)

    # -- sparse stage (CPU pool) ------------------------------------------
    def _sparse_forward(self, ids: np.ndarray) -> np.ndarray:
        from .fault_inject import fault_point
        fault_point("heter.pull")
        b, slots = ids.shape
        rows = self.table.pull(ids.reshape(-1))
        return np.asarray(rows, np.float32).reshape(
            b, slots * self.dim)

    def _sparse_backward(self, ids: np.ndarray,
                         d_acts: np.ndarray) -> None:
        from .fault_inject import fault_point
        fault_point("heter.push")
        self.table.push_grad(
            ids.reshape(-1),
            np.asarray(d_acts, np.float32).reshape(-1, self.dim))

    # -- pipeline driver ---------------------------------------------------
    def run(self, batches, sync: bool = False) -> list:
        """Train over ``batches`` (iterable of (ids [B, n_slots] int,
        labels)); returns the per-batch losses.

        ``sync=False`` (default, the reference async-PS semantics):
        the pool computes batch i+1's pulls while the device step for
        batch i is in flight, and gradient pushes drain asynchronously
        — one-step bounded staleness on rows shared between adjacent
        batches (the LAST push is joined before returning).
        ``sync=True``: each push completes before the next pull — the
        sync-PS lockstep; exact parity with a monolithic model."""
        batches = list(batches)
        losses = []
        pending_bwd = []
        fwd_fut = None
        for i, (ids, labels) in enumerate(batches):
            ids = np.asarray(ids)
            acts_np = (fwd_fut.result() if fwd_fut is not None
                       else self._sparse_forward(ids))
            # get_lr() per step: an attached LR scheduler must drive the
            # dense stage exactly as it would a monolithic TrainStep
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            self._params, self._opt_state, loss, ga = self._device_step(
                self._params, self._opt_state, jnp.asarray(acts_np),
                jnp.asarray(labels), lr)
            # device step dispatched (async): overlap the NEXT batch's
            # sparse forward with it before blocking on ga
            if not sync and i + 1 < len(batches):
                nxt = np.asarray(batches[i + 1][0])
                fwd_fut = self._pool.submit(self._sparse_forward, nxt)
            bwd = self._pool.submit(self._sparse_backward, ids,
                                    np.asarray(ga))
            if sync:
                bwd.result()
            else:
                pending_bwd.append(bwd)
                # fail fast: harvest pushes that already completed so a
                # failed push aborts the epoch NOW, not at the final
                # join after every remaining batch trained against a
                # table that silently missed updates
                still_pending = []
                first_exc = None
                for f in pending_bwd:
                    if f.done():
                        exc = f.exception()
                        if exc is not None and first_exc is None:
                            first_exc = exc
                    else:
                        still_pending.append(f)
                if first_exc is not None:
                    # join the in-flight pushes before unwinding — a
                    # pool thread must not keep mutating the table
                    # under the caller's error handling
                    for f in still_pending:
                        try:
                            f.result()
                        except Exception:
                            pass  # the first failure is the one raised
                    raise first_exc
                pending_bwd = still_pending
            losses.append(float(loss))
        for f in pending_bwd:
            f.result()
        return losses

    def shutdown(self) -> None:
        """Join and release the CPU worker pool (also runs on __exit__
        and best-effort on GC — a sweep constructing many trainers must
        not leak 2 worker threads per instance)."""
        self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def __del__(self):
        try:
            self._pool.shutdown(wait=False)
        except Exception:
            pass
