"""Fault-tolerant training runtime: retries, durable checkpoints,
heartbeat-driven recovery.

Reference parity: fleet/elastic.py treats failure as a first-class event
— etcd membership with the ELASTIC_EXIT_CODE=101 restart contract and
checkpoint-based recovery. This module is the piece our reproduction was
missing: the primitives in ``elastic.py`` (membership stores) and
``checkpoint.py`` (orbax save/load) wired into a loop that actually
survives faults, testable on CPU via ``fault_inject``:

- ``RetryPolicy`` — exponential backoff + seeded jitter + deadline,
  with a per-site override registry (``set_site_policy`` /
  ``PT_RETRY_SITES``). Applied to membership ops, checkpoint IO and PS
  client traffic.
- ``ResilientCheckpointManager`` — atomic tmp+rename checkpoint dirs,
  per-shard crc32 manifest, keep-N rotation, and
  ``restore_latest_valid()`` that SKIPS torn/partial/corrupt steps.
- ``HeartbeatMonitor`` — membership register + heartbeat on a thread,
  retried, with loss detection (the ElasticManager watch loop hardened
  against flaky stores).
- ``ResilientTrainer`` — runs a user step function under heartbeats,
  checkpoints every N steps, and on an injected or real fault restores
  the latest VALID checkpoint and replays — degrading gracefully
  (log + continue) instead of hanging or corrupting state.

Checkpoint layout (host-local; for multi-host sharded arrays layer this
manager's manifest over ``checkpoint.save_sharded``'s orbax output)::

    dir/step_00000020/
        manifest.json        # {"shards": {f: {crc32,size}}, "structure"}
        arr_0000.npy ...     # one shard per pytree leaf
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .fault_inject import MODE_TORN, fault_point

log = logging.getLogger("paddle_tpu.resilience")

_TRANSIENT = (ConnectionError, OSError, TimeoutError)
# OSError subclasses that are deterministic, not transient: retrying a
# missing path or a permission wall burns backoff time and masks the
# real exception type behind RetryExhausted.
_NEVER_RETRY = (FileNotFoundError, PermissionError, NotADirectoryError,
                IsADirectoryError, FileExistsError)


class RetryExhausted(RuntimeError):
    """All attempts of a retried op failed; ``__cause__`` is the last
    underlying error."""

    def __init__(self, site: str, attempts: int, reason: str = ""):
        msg = f"retry exhausted after {attempts} attempt(s)"
        if site:
            msg += f" at site {site!r}"
        if reason:
            msg += f" ({reason})"
        super().__init__(msg)
        self.site = site
        self.attempts = attempts


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with seeded jitter and an optional deadline.

    ``retry_on`` lists the exception classes considered transient —
    everything else propagates immediately (a server-side KeyError is
    not going to succeed on attempt 2). InjectedFault subclasses
    ConnectionError, so armed fault sites exercise this path."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25
    timeout_s: Optional[float] = None
    retry_on: tuple = _TRANSIENT
    seed: int = 0

    def preview_delays(self) -> List[float]:
        """The deterministic delay sequence this policy would sleep
        (one entry per retry, i.e. max_attempts - 1 entries)."""
        rng = np.random.default_rng(self.seed)
        out = []
        for attempt in range(max(0, self.max_attempts - 1)):
            out.append(self._delay(attempt, rng))
        return out

    def _delay(self, attempt: int, rng) -> float:
        d = min(self.base_delay_s * self.multiplier ** attempt,
                self.max_delay_s)
        return d * (1.0 + self.jitter * float(rng.random()))

    def call(self, fn: Callable, *args, site: str = "",
             on_retry: Optional[Callable] = None, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.
        ``max_attempts`` below 1 (a PT_RETRY_SITES typo) is clamped to
        1 — the op must run at least once, never silently no-op."""
        attempts = max(1, self.max_attempts)
        rng = np.random.default_rng(self.seed)
        deadline = (time.monotonic() + self.timeout_s
                    if self.timeout_s is not None else None)
        for attempt in range(attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                if isinstance(e, _NEVER_RETRY):
                    raise  # deterministic: keep the original type
                if attempt + 1 >= attempts:
                    raise RetryExhausted(site, attempt + 1) from e
                delay = self._delay(attempt, rng)
                if deadline is not None and \
                        time.monotonic() + delay > deadline:
                    raise RetryExhausted(site, attempt + 1,
                                         "deadline exceeded") from e
                log.warning("retry %d/%d at %s after %s: sleeping %.3fs",
                            attempt + 1, self.max_attempts - 1,
                            site or "<op>", type(e).__name__, delay)
                if on_retry is not None:
                    on_retry(attempt + 1, e, delay)
                time.sleep(delay)

    @classmethod
    def from_spec(cls, spec: str, **defaults) -> "RetryPolicy":
        """Parse ``attempts=5,base=0.01,max_delay=1,mult=2,jitter=0,
        timeout=3`` (the PT_RETRY_SITES value format)."""
        kw: Dict[str, Any] = dict(defaults)
        keymap = {"attempts": ("max_attempts", int),
                  "base": ("base_delay_s", float),
                  "max_delay": ("max_delay_s", float),
                  "mult": ("multiplier", float),
                  "jitter": ("jitter", float),
                  "timeout": ("timeout_s", float),
                  "seed": ("seed", int)}
        for kv in filter(None, spec.split(",")):
            k, _, v = kv.partition("=")
            entry = keymap.get(k.strip())
            if entry is None or not v:
                # a PT_RETRY_SITES typo must not crash the first
                # retried op deep inside a training step
                log.warning("PT_RETRY_SITES: ignoring malformed entry "
                            "%r (known keys: %s)", kv,
                            ", ".join(sorted(keymap)))
                continue
            name, conv = entry
            kw[name] = conv(v)
        return cls(**kw)


DEFAULT_RETRY = RetryPolicy()
NO_RETRY = RetryPolicy(max_attempts=1)

# Per-site BUILT-IN defaults (overridable via set_site_policy /
# PT_RETRY_SITES like any site). serving.prefill sits on the serving
# admission path (serving/server.py + inference/continuous_batching):
# a transient prefill failure should be retried promptly — a queued
# client is waiting on its TTFT — and give up fast enough that the
# engine's per-request attempt budget (max_prefill_attempts) can fail
# the request with a typed reply instead of wedging admission.
_BUILTIN_SITE_POLICIES: Dict[str, "RetryPolicy"] = {
    "serving.prefill": RetryPolicy(max_attempts=3, base_delay_s=0.02,
                                   max_delay_s=0.25),
    # serving.verify guards the speculative draft-and-verify step
    # (inference/continuous_batching._spec_step): same regime as
    # prefill — every active slot's clients are waiting on the step,
    # so retry transients promptly and give up fast (a persistent
    # failure escalates through the server's engine-error cap)
    "serving.verify": RetryPolicy(max_attempts=3, base_delay_s=0.02,
                                  max_delay_s=0.25),
    # the IO-bound training sites ride the stock policy; listing them
    # explicitly is what the fault-site registry audit pins — a new
    # fault site must declare its retry disposition here or in
    # NO_RETRY_SITES, never implicitly
    "checkpoint.write": DEFAULT_RETRY,
    "checkpoint.read": DEFAULT_RETRY,
    # hot-swap checkpoint load (serving swap op, conn thread): same
    # IO-bound regime as checkpoint.read — transient faults retry via
    # the stock policy; a persistent or corrupt load exhausts retries
    # and surfaces as a typed SwapFailed with the old weights pinned
    "checkpoint.load": DEFAULT_RETRY,
    "membership.heartbeat": DEFAULT_RETRY,
    "ps.push": DEFAULT_RETRY,
    "ps.pull": DEFAULT_RETRY,
    "ps.call": DEFAULT_RETRY,
    "dataloader.fetch": DEFAULT_RETRY,
}

# Sites that are DELIBERATELY not retried in place: recovery is owned
# by a higher layer, and an in-place retry would duplicate (or fight)
# it. The registry-audit test requires every fault_inject.FAULT_SITES
# entry to appear either in _BUILTIN_SITE_POLICIES or here.
NO_RETRY_SITES: Dict[str, str] = {
    "trainer.step": "recovery is checkpoint restore + replay "
                    "(ResilientTrainer), not an in-place retry",
    "collective.step": "a failed collective desyncs the group; the "
                       "trainer-level restore owns recovery",
    "heter.push": "async PS semantics: errors drain per-iteration and "
                  "degrade the batch, they are not replayed",
    "heter.pull": "async PS semantics: errors drain per-iteration and "
                  "degrade the batch, they are not replayed",
    "serving.request": "client-facing: the server answers a retryable "
                       "typed reply and the CLIENT owns the retry",
    "engine.step": "the serving loop counts consecutive failures; "
                   "recovery is engine resurrection + in-flight "
                   "replay (serving/server.py), not a per-step retry",
    "alloc.page": "admission unwinds and requeues the request; the "
                  "next engine step retries admission naturally",
    "net.recv": "connection-level: the failover router resubmits "
                "keyed requests to a live replica "
                "(serving/supervisor.py)",
    "cache.spill": "a failed or corrupt spill blob degrades to a "
                   "prefix-cache miss and the chained-prefill "
                   "fallback recomputes the pages "
                   "(serving/prefix_cache.py); retrying the blob IO "
                   "in place would buy nothing the fallback doesn't",
    "swap.apply": "the swap caller owns recovery: an abort here "
                  "surfaces as a typed SwapFailed with the old "
                  "generation still serving, and the supervisor's "
                  "roll/rollback path decides whether to re-issue "
                  "the swap — a blind in-place retry could "
                  "double-apply against a live engine",
}

_site_policies: Dict[str, RetryPolicy] = {}
_env_policies: Optional[Dict[str, RetryPolicy]] = None
_policy_lock = threading.Lock()


def set_site_policy(site: str, policy: Optional[RetryPolicy]) -> None:
    """Override the retry policy for one site (None removes)."""
    with _policy_lock:
        if policy is None:
            _site_policies.pop(site, None)
        else:
            _site_policies[site] = policy


def clear_site_policies() -> None:
    with _policy_lock:
        _site_policies.clear()


def _load_env_policies() -> Dict[str, RetryPolicy]:
    global _env_policies
    if _env_policies is None:
        out: Dict[str, RetryPolicy] = {}
        raw = os.environ.get("PT_RETRY_SITES", "").strip()
        for entry in filter(None, (e.strip() for e in raw.split(";"))):
            site, _, spec = entry.partition(":")
            out[site.strip()] = RetryPolicy.from_spec(spec)
        _env_policies = out
    return _env_policies


def get_retry_policy(site: str) -> RetryPolicy:
    """Resolution order: programmatic override > PT_RETRY_SITES env >
    built-in site default > DEFAULT_RETRY."""
    with _policy_lock:
        p = _site_policies.get(site)
    if p is not None:
        return p
    env = _load_env_policies().get(site)
    if env is not None:
        return env
    return _BUILTIN_SITE_POLICIES.get(site, DEFAULT_RETRY)


def call_with_retry(site: str, fn: Callable, *args, **kwargs):
    return get_retry_policy(site).call(fn, *args, site=site, **kwargs)


# ---------------------------------------------------------------------------
# Durable checkpoints
# ---------------------------------------------------------------------------

class CheckpointCorruptError(RuntimeError):
    pass


def _flatten_tree(obj, path: str, leaves: Dict[str, np.ndarray]):
    """Encode a dict/list/tuple/array pytree into a JSON structure whose
    leaves reference .npy shard names."""
    if isinstance(obj, dict):
        return {"kind": "dict",
                "items": {str(k): _flatten_tree(v, f"{path}.{k}", leaves)
                          for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        kind = "list" if isinstance(obj, list) else "tuple"
        return {"kind": kind,
                "items": [_flatten_tree(v, f"{path}[{i}]", leaves)
                          for i, v in enumerate(obj)]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"kind": "scalar", "value": obj}
    name = f"arr_{len(leaves):04d}.npy"
    leaves[name] = np.asarray(obj)
    return {"kind": "leaf", "shard": name}


def _unflatten_tree(node, arrays: Dict[str, np.ndarray]):
    kind = node["kind"]
    if kind == "dict":
        return {k: _unflatten_tree(v, arrays)
                for k, v in node["items"].items()}
    if kind == "list":
        return [_unflatten_tree(v, arrays) for v in node["items"]]
    if kind == "tuple":
        return tuple(_unflatten_tree(v, arrays) for v in node["items"])
    if kind == "scalar":
        return node["value"]
    return arrays[node["shard"]]


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


class ResilientCheckpointManager:
    """Step-indexed checkpoints with atomic writes, per-shard checksums
    and keep-N rotation; restore skips anything that fails validation.

    Writes go to a ``.tmp-*`` sibling and are renamed into place only
    once every shard and the manifest are on disk, so a crash mid-write
    never leaves a step directory that LOOKS complete. Torn writes that
    did get renamed (simulated by the ``checkpoint.write`` fault site's
    "torn" mode, or real bitrot) are caught at read time by the crc32
    manifest and skipped by ``restore_latest_valid``."""

    MANIFEST = "manifest.json"

    def __init__(self, directory: str, keep_n: int = 3,
                 retry: Optional[RetryPolicy] = None):
        self.directory = os.path.abspath(directory)
        self.keep_n = max(1, int(keep_n))
        self.retry = retry
        self.last_skipped: List[int] = []
        self._seq = 0
        # steps THIS manager wrote cleanly (no injected torn write):
        # lets rotation skip re-checksumming multi-GB steps it just
        # wrote; restore paths still always validate from disk
        self._written_ok: set = set()
        os.makedirs(self.directory, exist_ok=True)

    # -- naming ------------------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self) -> List[int]:
        out = []
        for fn in os.listdir(self.directory):
            if fn.startswith("step_"):
                try:
                    out.append(int(fn[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- write -------------------------------------------------------------

    def save(self, step: int, state: Any) -> str:
        """Write ``state`` (nested dict/list/tuple of arrays + scalars)
        as checkpoint ``step``. Retried per the "checkpoint.write" site
        policy; returns the final directory path."""
        policy = self.retry or get_retry_policy("checkpoint.write")
        path = policy.call(self._write_once, step, state,
                           site="checkpoint.write")
        self._gc()
        return path

    def _write_once(self, step: int, state: Any) -> str:
        self._seq += 1
        tmp = os.path.join(
            self.directory,
            f".tmp-step_{step:08d}-{os.getpid()}-{self._seq}")
        final = self._step_dir(step)
        os.makedirs(tmp)
        try:
            leaves: Dict[str, np.ndarray] = {}
            structure = _flatten_tree(state, "", leaves)
            shards = {}
            for name, arr in leaves.items():
                p = os.path.join(tmp, name)
                with open(p, "wb") as f:
                    np.save(f, arr, allow_pickle=False)
                shards[name] = {"crc32": _crc32_file(p),
                                "size": os.path.getsize(p)}
            manifest = {"format": 1, "step": int(step),
                        "shards": shards, "structure": structure}
            with open(os.path.join(tmp, self.MANIFEST), "w") as f:
                json.dump(manifest, f)
            mode = fault_point("checkpoint.write",
                               modes=(MODE_TORN,))  # may raise (abort)
            if mode == MODE_TORN and shards:
                # simulate a write that was acknowledged but landed
                # corrupt: truncate one shard AFTER its checksum was
                # recorded, then publish the step anyway
                victim = os.path.join(tmp, sorted(shards)[0])
                with open(victim, "r+b") as f:
                    f.truncate(max(0, os.path.getsize(victim) // 2))
                self._written_ok.discard(step)
            if os.path.exists(final):
                shutil.rmtree(final)  # retry overwriting a torn step
            os.rename(tmp, final)
            if mode != MODE_TORN:
                self._written_ok.add(step)
            return final
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def _gc(self) -> None:
        steps = self.all_steps()
        doomed = steps[:-self.keep_n]
        if doomed:
            # rotation must never strand the run on corrupt-only steps:
            # the newest VALID step survives even outside the window.
            # Steps this manager wrote cleanly skip the disk re-read
            # (a full crc pass per save would double checkpoint I/O).
            newest_valid = next(
                (s for s in reversed(steps)
                 if s in self._written_ok or self.validate(s)), None)
            for step in doomed:
                if step == newest_valid:
                    continue
                self._written_ok.discard(step)
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
        for fn in os.listdir(self.directory):
            if fn.startswith(".tmp-"):
                # stale tmp from a crashed writer in another life; a
                # live writer's tmp dirs use our pid+seq so no clash
                p = os.path.join(self.directory, fn)
                if f"-{os.getpid()}-" not in fn:
                    shutil.rmtree(p, ignore_errors=True)

    # -- read --------------------------------------------------------------

    def validate(self, step: int) -> bool:
        """True iff the step's manifest parses and every shard matches
        its recorded size + crc32."""
        d = self._step_dir(step)
        try:
            with open(os.path.join(d, self.MANIFEST)) as f:
                manifest = json.load(f)
            for name, meta in manifest["shards"].items():
                p = os.path.join(d, name)
                if os.path.getsize(p) != meta["size"] or \
                        _crc32_file(p) != meta["crc32"]:
                    return False
            return True
        except (OSError, ValueError, KeyError):
            return False

    def restore(self, step: int) -> Any:
        """Load checkpoint ``step``; raises CheckpointCorruptError when
        validation fails."""
        fault_point("checkpoint.read")
        if not self.validate(step):
            raise CheckpointCorruptError(
                f"checkpoint step {step} at {self._step_dir(step)} is "
                "missing, partial, or fails its checksum manifest")
        d = self._step_dir(step)
        with open(os.path.join(d, self.MANIFEST)) as f:
            manifest = json.load(f)
        arrays = {name: np.load(os.path.join(d, name), allow_pickle=False)
                  for name in manifest["shards"]}
        return _unflatten_tree(manifest["structure"], arrays)

    def restore_latest_valid(self) -> Optional[Tuple[int, Any]]:
        """Walk steps newest-first, skipping corrupt/partial ones;
        returns (step, state) or None. Skipped steps are recorded in
        ``last_skipped``."""
        self.last_skipped = []
        policy = self.retry or get_retry_policy("checkpoint.read")
        for step in reversed(self.all_steps()):
            try:
                state = policy.call(self.restore, step,
                                    site="checkpoint.read")
                return step, state
            except (CheckpointCorruptError, RetryExhausted) as e:
                self.last_skipped.append(step)
                log.warning("skipping checkpoint step %d: %s", step, e)
        return None


# ---------------------------------------------------------------------------
# Heartbeats
# ---------------------------------------------------------------------------

class HeartbeatMonitor:
    """Registers a rank with a MembershipStore and heartbeats it on a
    daemon thread, retrying transient store failures. After
    ``lost_after`` consecutive failed beats the rank is considered
    disconnected: ``healthy()`` flips, ``on_lost`` fires (once per
    outage) and the monitor keeps trying to re-register — the hardened
    version of ElasticManager's bare loop, whose heartbeat exception
    would silently kill the watch thread."""

    def __init__(self, store, job_id: str, rank: int,
                 interval_s: float = 1.0, meta: Optional[Dict] = None,
                 retry: Optional[RetryPolicy] = None, lost_after: int = 3,
                 on_lost: Optional[Callable[[], None]] = None):
        self.store = store
        self.job_id = job_id
        self.rank = rank
        self.interval_s = interval_s
        self.meta = dict(meta or {})
        self.retry = retry
        self.lost_after = max(1, int(lost_after))
        self.on_lost = on_lost
        self.consecutive_failures = 0
        self.beats = 0
        self._lost_fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _policy(self) -> RetryPolicy:
        return self.retry or get_retry_policy("membership.heartbeat")

    def start(self) -> "HeartbeatMonitor":
        self._policy().call(self.store.register, self.job_id, self.rank,
                            self.meta, site="membership.register")
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._policy().call(self.store.heartbeat, self.job_id,
                                    self.rank,
                                    site="membership.heartbeat")
            except Exception as e:  # noqa: BLE001 - the monitor thread
                # must survive anything the store throws
                self.consecutive_failures += 1
                log.warning("heartbeat failed (%d consecutive): %s",
                            self.consecutive_failures, e)
                if self.consecutive_failures >= self.lost_after and \
                        not self._lost_fired:
                    self._lost_fired = True
                    if self.on_lost is not None:
                        try:
                            self.on_lost()
                        except Exception:
                            log.exception("on_lost callback failed")
                try:  # expired entries need a fresh register (lease
                    # semantics: a late heartbeat cannot resurrect)
                    self.store.register(self.job_id, self.rank, self.meta)
                except Exception:
                    pass
            else:
                self.beats += 1
                self.consecutive_failures = 0
                self._lost_fired = False
            self._stop.wait(self.interval_s)

    def healthy(self) -> bool:
        return self.consecutive_failures < self.lost_after

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.store.deregister(self.job_id, self.rank)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Resilient training loop
# ---------------------------------------------------------------------------

@dataclass
class TrainerEvent:
    kind: str  # checkpoint | checkpoint_failed | step_fault | restore |
    #            degraded | recovered
    step: int
    detail: str = ""


class ResilientTrainer:
    """Drives ``step_fn(state, batch) -> (state, loss)`` to completion
    under faults.

    Every ``checkpoint_every`` completed steps the full state (plus the
    loss history, so a replayed run is indistinguishable) is written
    through a ResilientCheckpointManager. When a step raises — an
    injected fault, a preempted host's ConnectionError, anything short
    of KeyboardInterrupt — the trainer restores the latest VALID
    checkpoint and replays from that step. Because ``step_fn`` is
    deterministic and restores are exact (npy round-trip), the final
    params match a fault-free run bit-for-bit. A deterministic bug that
    keeps faulting exhausts ``max_restores`` and surfaces.

    Checkpoint-write failures degrade gracefully: logged, training
    continues on the previous checkpoint's protection. An unhealthy
    heartbeat is reported as a "degraded" event, not a crash."""

    def __init__(self, step_fn: Callable, state: Any,
                 checkpoint: ResilientCheckpointManager,
                 checkpoint_every: int = 5, max_restores: int = 3,
                 heartbeat: Optional[HeartbeatMonitor] = None,
                 on_event: Optional[Callable[[TrainerEvent], None]] = None):
        self.step_fn = step_fn
        self.state = state
        self.ckpt = checkpoint
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.max_restores = int(max_restores)
        self.heartbeat = heartbeat
        self.on_event = on_event
        self.events: List[TrainerEvent] = []
        self.restores = 0
        self.losses: List[float] = []

    def _event(self, kind: str, step: int, detail: str = "") -> None:
        ev = TrainerEvent(kind, step, detail)
        self.events.append(ev)
        log.info("trainer event %s at step %d: %s", kind, step, detail)
        if self.on_event is not None:
            self.on_event(ev)

    def _payload(self) -> Dict[str, Any]:
        return {"state": self.state,
                "losses": np.asarray(self.losses, np.float64)}

    def _save(self, step: int) -> None:
        try:
            self.ckpt.save(step, self._payload())
            self._event("checkpoint", step)
        except Exception as e:  # degrade: keep training on the older one
            self._event("checkpoint_failed", step, repr(e))

    def _latest_valid(self):
        """restore_latest_valid + event trail for any corrupt steps it
        skipped (shared by crash recovery and process-restart resume)."""
        found = self.ckpt.restore_latest_valid()
        for skipped in self.ckpt.last_skipped:
            self._event("restore_skipped_corrupt", skipped)
        return found

    def _apply_payload(self, found) -> int:
        step, payload = found
        self.state = payload["state"]
        self.losses = list(np.asarray(payload["losses"]).tolist())
        return step

    def _restore(self, initial_state) -> int:
        """Roll back to the latest valid checkpoint (or the initial
        state); returns the step index to resume from."""
        found = self._latest_valid()
        if found is None:
            self.state = initial_state
            self.losses = []
            self._event("restore", 0, "no valid checkpoint; from init")
            return 0
        step = self._apply_payload(found)
        self._event("restore", step)
        return step

    def run(self, batches) -> List[float]:
        """Train over ``batches`` (a replayable sequence); returns the
        per-step losses. ``self.state`` holds the final state."""
        batches = list(batches)
        initial_state = self.state
        resumed = self._latest_valid()
        if resumed is not None:
            i = self._apply_payload(resumed)
            self._event("resume", i)
        else:
            i = 0
            self._save(0)
        high_water = i  # furthest step ever completed this run
        hb_healthy = True
        while i < len(batches):
            if self.heartbeat is not None:
                now_healthy = self.heartbeat.healthy()
                if hb_healthy and not now_healthy:
                    self._event("degraded", i, "membership heartbeat lost")
                elif not hb_healthy and now_healthy:
                    self._event("recovered", i)
                hb_healthy = now_healthy
            try:
                fault_point("trainer.step")
                self.state, loss = self.step_fn(self.state, batches[i])
                self.losses.append(float(loss))
                i += 1
                if i > high_water:
                    # NEW territory reached: earlier faults were
                    # transient, so the restore budget refills. A
                    # deterministic bug keeps crashing at the same
                    # step, never passes its high-water mark, and
                    # still exhausts max_restores.
                    high_water = i
                    self.restores = 0
                if i % self.checkpoint_every == 0:
                    self._save(i)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 - every fault class
                # funnels through checkpoint recovery
                self._event("step_fault", i, repr(e))
                self.restores += 1
                if self.restores > self.max_restores:
                    log.error("max_restores=%d exceeded; giving up",
                              self.max_restores)
                    raise
                i = self._restore(initial_state)
        return self.losses
