"""Deterministic fault injection for the resilience subsystem.

Reference parity: the reference validates its fleet fault tolerance with
chaos-style unittests (test_fleet_elastic_*, test_dist_fleet_* kill the
trainer/PS process mid-run); TPU pods see the same failure classes in
production — host preemption, slow ranks, torn checkpoint writes. This
module makes every one of those paths testable ON CPU by raising (or
silently corrupting, for torn-write simulation) at named sites threaded
through the runtime:

==================== =================================================
site                 where it fires
==================== =================================================
checkpoint.write     resilience.ResilientCheckpointManager.save /
                     checkpoint.save_sharded
checkpoint.read      ...Manager.restore / checkpoint.load_sharded
membership.heartbeat elastic.{File,Tcp}MembershipStore.heartbeat
ps.push / ps.pull    ps.PSClient push/pull traffic
heter.push/heter.pull heter.HeterPipelineTrainer sparse stage
dataloader.fetch     io.dataloader worker batch assembly
collective.step      collective.all_reduce / barrier (eager host path)
trainer.step         resilience.ResilientTrainer per-step gate
serving.request      serving/server.py per-request front-end handling
                     (clients receive a retryable typed error reply)
serving.prefill      inference/continuous_batching engine admission
                     prefill (retried per the serving.prefill policy;
                     exhausted retries FAIL the request with a typed
                     reply instead of wedging the queue)
serving.verify       inference/continuous_batching speculative
                     draft-and-verify step (retried per the
                     serving.verify policy; fires BEFORE the donating
                     jit runs, so a retry never sees consumed buffers)
engine.step          inference/continuous_batching engine step, FIRST
                     thing — before admission and the donating jit, so
                     host/device state is untouched; persistent firing
                     drives the server's engine-resurrection path
alloc.page           inference/continuous_batching PageAllocator
                     alloc/reserve (before any free-list mutation);
                     admission unwinds and requeues the request
net.recv             serving/server.py connection reader and the
                     supervisor's failover-router backend reader —
                     the connection dies like a torn socket; keyed
                     requests are resubmitted to a live replica
cache.spill          serving/prefix_cache.py spill-tier blob write
                     (eviction) and read (restore); "torn" corrupts
                     the written blob so the restore-side crc32 must
                     catch it — either way the page degrades to a
                     cache miss and chained prefill recomputes it
==================== =================================================

Default-OFF: with no sites armed (the tier-1 default), ``fault_point``
is a single module-bool check. Arm programmatically::

    inj = get_injector()
    inj.arm("checkpoint.write", at_calls=[2], mode="torn")
    inj.arm("ps.push", probability=0.2, max_faults=3, seed=7)

or from the environment (read once, at first ``get_injector()``)::

    PT_FAULT_INJECT="checkpoint.write:at=2,mode=torn;ps.push:p=0.2,max=3"
    PT_FAULT_SEED=7

Schedules are deterministic: probabilistic firing draws from a
per-site ``numpy`` Generator seeded at arm time, and ``at_calls`` fires
on exact 1-based call indices — the same arming always yields the same
fault sequence, so recovery tests are reproducible.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional

import numpy as np

# The canonical fault-site registry: every site string passed to
# fault_point() anywhere in the tree MUST have an entry here (enforced
# by the registry-audit test), and every entry must carry a one-line
# docstring plus a retry disposition in distributed/resilience.py —
# either a get_retry_policy entry (_BUILTIN_SITE_POLICIES / default)
# or an explicit NO_RETRY_SITES marker explaining who owns recovery.
FAULT_SITES: Dict[str, str] = {
    "checkpoint.write": "durable checkpoint save (manager + sharded)",
    "checkpoint.read": "checkpoint restore / load_sharded",
    "membership.heartbeat": "elastic membership store heartbeat",
    "ps.push": "parameter-server gradient push",
    "ps.pull": "parameter-server weight pull",
    "ps.call": "parameter-server control-plane RPC (barrier/stop/...)",
    "heter.push": "heterogeneous sparse-stage gradient push",
    "heter.pull": "heterogeneous sparse-stage embedding pull",
    "dataloader.fetch": "dataloader worker batch assembly",
    "collective.step": "eager-host collective op (all_reduce/barrier)",
    "trainer.step": "ResilientTrainer per-step gate",
    "serving.request": "serving front-end per-request handling",
    "serving.prefill": "decode-engine admission prefill",
    "serving.verify": "speculative draft-and-verify step",
    "engine.step": "decode-engine step (pre-admission, pre-jit)",
    "alloc.page": "page-allocator alloc/reserve (pre-mutation)",
    "net.recv": "connection receive (server + failover router)",
    "cache.spill": "prefix-cache spill-tier blob write/read "
                   "(serving/prefix_cache.py; write side implements "
                   "'torn' — a corrupted blob the restore-side crc32 "
                   "must catch; either side degrades to a cache miss "
                   "and the chained-prefill fallback recomputes)",
    "checkpoint.load": "hot-swap checkpoint load+validate (serving "
                       "swap op, conn thread; transient IO faults "
                       "retry via the builtin policy, a persistent/"
                       "corrupt load fails as a typed SwapFailed "
                       "with the old weights still serving)",
    "swap.apply": "engine weight-swap apply (fires after validation, "
                  "before the first tensor write — an abort here "
                  "proves the all-or-nothing swap contract)",
}

# Fast-path gate: False whenever no injector exists or no site is armed,
# so production fault_point() calls cost one global read.
_ACTIVE = False
_GLOBAL: Optional["FaultInjector"] = None
_LOCK = threading.Lock()

# Modes: "abort" raises InjectedFault at the site; "torn" asks the site
# to complete a *corrupted* write and report success (only the
# checkpoint-write site implements it; elsewhere it degrades to abort).
MODE_ABORT = "abort"
MODE_TORN = "torn"


class InjectedFault(ConnectionError):
    """Raised by an armed fault site. Subclasses ConnectionError so the
    default RetryPolicy transient-set retries it — an injected fault is
    a stand-in for exactly that class of failure."""

    def __init__(self, site: str, index: int, mode: str = MODE_ABORT):
        super().__init__(
            f"injected fault at site {site!r} (call #{index}, {mode})")
        self.site = site
        self.index = index
        self.mode = mode


@dataclass
class FaultSpec:
    """Arming schedule for one site."""

    probability: float = 0.0
    at_calls: FrozenSet[int] = frozenset()  # 1-based call indices
    max_faults: Optional[int] = None
    mode: str = MODE_ABORT
    exc: Optional[type] = None  # exception class; default InjectedFault
    seed: int = 0
    calls: int = 0
    fired: int = 0
    _rng: np.random.Generator = field(default=None, repr=False)

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.max_faults is not None and self.fired >= self.max_faults:
            return False
        if self.calls in self.at_calls:
            return True
        if self.probability > 0.0 and \
                self._rng.random() < self.probability:
            return True
        return False


class FaultInjector:
    """Registry of armed sites; ``fire`` is the hot entry point."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._specs: Dict[str, FaultSpec] = {}
        self._lock = threading.Lock()
        self.log: List[InjectedFault] = []

    def arm(self, site: str, probability: float = 0.0, at_calls=(),
            max_faults: Optional[int] = None, mode: str = MODE_ABORT,
            exc: Optional[type] = None, seed: Optional[int] = None
            ) -> "FaultInjector":
        global _ACTIVE
        with self._lock:
            self._specs[site] = FaultSpec(
                probability=probability,
                at_calls=frozenset(int(c) for c in at_calls),
                max_faults=max_faults, mode=mode, exc=exc,
                seed=self.seed if seed is None else seed)
        _ACTIVE = True
        return self

    def disarm(self, site: Optional[str] = None) -> None:
        global _ACTIVE
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)
            if not self._specs:
                _ACTIVE = False

    def armed(self, site: str) -> bool:
        return site in self._specs

    def counts(self, site: str) -> Dict[str, int]:
        spec = self._specs.get(site)
        return {"calls": spec.calls, "fired": spec.fired} if spec else \
            {"calls": 0, "fired": 0}

    def fire(self, site: str,
             modes: tuple = (MODE_ABORT,)) -> Optional[str]:
        """Consult the site's schedule. Raises on an "abort" fault;
        returns the mode string for non-abort modes the SITE declares
        it implements via ``modes`` (e.g. the checkpoint-write site
        passes ("abort", "torn")). A mode the site does NOT implement
        degrades to abort rather than silently counting as fired
        without any effect. Returns None when nothing fires."""
        with self._lock:
            spec = self._specs.get(site)
            if spec is None or not spec.should_fire():
                return None
            spec.fired += 1
            # the logged instance is NEVER the raised one: a raised
            # exception carries __traceback__, and retaining it here
            # would pin every frame on the faulting call stack (and
            # everything those frames reference — sockets, buffers,
            # engine state) for the injector's lifetime. A half-open
            # connection whose fd hides in a logged traceback is a
            # hang, not a chaos test.
            logged = InjectedFault(site, spec.calls, spec.mode)
            self.log.append(logged)
            if spec.mode == MODE_ABORT or spec.mode not in modes:
                if spec.exc is not None:
                    raise spec.exc(str(logged))
                raise InjectedFault(site, spec.calls, spec.mode)
            return spec.mode

    def configure_from_env(self, env=None) -> "FaultInjector":
        """Parse ``PT_FAULT_INJECT``: ``site:k=v,k=v;site2:...`` with
        keys p (probability), at (``|``-separated call indices), max,
        mode, seed."""
        env = os.environ if env is None else env
        raw = env.get("PT_FAULT_INJECT", "").strip()
        if not raw:
            return self
        for entry in raw.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            site, _, spec = entry.partition(":")
            kw: Dict = {}
            for kv in filter(None, spec.split(",")):
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                if k == "p":
                    kw["probability"] = float(v)
                elif k == "at":
                    kw["at_calls"] = [int(x) for x in v.split("|") if x]
                elif k == "max":
                    kw["max_faults"] = int(v)
                elif k == "mode":
                    kw["mode"] = v
                elif k == "seed":
                    kw["seed"] = int(v)
            self.arm(site.strip(), **kw)
        return self


def get_injector() -> FaultInjector:
    """The process-wide injector (created on first use; env-armed)."""
    global _GLOBAL
    with _LOCK:
        if _GLOBAL is None:
            _GLOBAL = FaultInjector(
                seed=int(os.environ.get("PT_FAULT_SEED", "0")))
            _GLOBAL.configure_from_env()
    return _GLOBAL


def reset() -> None:
    """Drop the global injector (tests)."""
    global _GLOBAL, _ACTIVE
    with _LOCK:
        _GLOBAL = None
        _ACTIVE = False


def fault_point(site: str, modes: tuple = (MODE_ABORT,)
                ) -> Optional[str]:
    """Injection site hook. No-op (one bool read) unless a site is armed
    anywhere in the process. ``modes`` declares which non-abort modes
    this site implements; anything else raises InjectedFault (abort)."""
    if not _ACTIVE:
        return None
    return get_injector().fire(site, modes)


# A process launched with PT_FAULT_INJECT set must be armed without any
# explicit get_injector() call (the sites only check the _ACTIVE fast
# path); env set AFTER import requires calling get_injector() once.
if os.environ.get("PT_FAULT_INJECT", "").strip():
    get_injector()
