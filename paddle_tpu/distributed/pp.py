"""Pipeline parallelism.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py (LayerDesc:44, SharedLayerDesc:62,
PipelineLayer:76) + pipeline_parallel.py train_batch micro-batch loop and
the C++ SectionWorker F-then-B / 1F1B schedules
(paddle/fluid/framework/section_worker.cc:130-180).

TPU-native design: a pipeline stage is a position along the "pp" mesh
axis. Inside ONE jitted SPMD program, ``spmd_pipeline`` runs the classic
collective-permute microbatch loop: every device applies ITS stage's
params each step and ppermutes activations to the next stage. jax.grad
through the loop reverses the permutes, yielding the F-then-B schedule;
XLA overlaps the permute hop with the next microbatch's compute. The
reference's send_v2/recv_v2 + per-microbatch scopes collapse into this
scan. ``spmd_pipeline_1f1b`` is the true 1F1B schedule: interleaved
forward/backward ticks with manual vjp composition, bounding in-flight
activations at O(pp) regardless of microbatch count (``remat=True`` on
the F-then-B path only trades FLOPs for memory within a microbatch).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
from ..compat import axis_size as _compat_axis_size
import jax.numpy as jnp

from ..core.offload import remat_policy as _remat_policy
from ..nn.layer import Layer
from ..nn.container import LayerList


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py:44)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages, e.g. tied embeddings
    (reference: pp_layers.py:62; weight sync pp_layers.py:180-188)."""

    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Segments a LayerDesc list across pipeline stages
    (reference: pp_layers.py:76 PipelineLayer).

    Eager/forward semantics run the full stack (correct on any device
    count); the SPMD pipelined execution is built by ``spmd_pipeline``
    over the uniform block segment. ``seg_method="layer:<ClassName>"``
    marks which class forms the uniform pipelined body, as in the
    reference's "layer:TransformerBlock" convention.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, num_virtual_pipeline_stages=1):
        super().__init__()
        self.descs = list(layers)
        self.loss_fn = loss_fn
        self.num_stages = num_stages or 1
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval
        self.shared_layers = {}
        built: List[Layer] = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self.shared_layers:
                    built.append(self.shared_layers[d.layer_name])
                else:
                    layer = d.build_layer()
                    self.shared_layers[d.layer_name] = layer
                    built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # bare callable (e.g. lambda reshape)
                built.append(d)
        self.run_function = built
        self._layers = LayerList([b for b in built if isinstance(b, Layer)])

    def forward(self, x, **kwargs):
        for fn in self.run_function:
            x = fn(x)
        return x

    def get_stage_layers(self, stage: int, num_stages: Optional[int] = None
                         ) -> List:
        n = num_stages or self.num_stages
        per = (len(self.run_function) + n - 1) // n
        return self.run_function[stage * per:(stage + 1) * per]


def spmd_pipeline(stage_fn: Callable, stage_params: Any, x_micro,
                  axis_name: str = "pp", remat: bool = False):
    """Collective-permute pipeline over the pp mesh axis (call inside
    shard_map).

    stage_fn(params, x) -> y with matching x/y shapes; ``stage_params``
    are THIS device's stage weights (callers shard a stacked
    [n_stages, ...] pytree over the pp axis). x_micro: [n_micro, mb, ...]
    microbatched input (meaningful on stage 0; replicated elsewhere).
    Returns [n_micro, mb, ...] outputs valid on the LAST stage (zeros
    elsewhere); reduce with a pp-psum or mask as needed.
    """
    n_stages = _compat_axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total_steps = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    fn = jax.checkpoint(stage_fn, policy=_remat_policy()) \
        if remat else stage_fn

    def body(carry, t):
        recv_buf, outputs = carry
        mb_idx = jnp.clip(t - stage, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, jnp.clip(
            t, 0, n_micro - 1), keepdims=False)
        inp = jnp.where(stage == 0, first_in, recv_buf)
        out = fn(stage_params, inp)
        active = (t >= stage) & (t - stage < n_micro)
        out = jnp.where(active, out, jnp.zeros_like(out))
        # collect on the last stage
        is_last = stage == n_stages - 1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(active & is_last, out,
                      jax.lax.dynamic_index_in_dim(outputs, mb_idx,
                                                   keepdims=False)),
            mb_idx, axis=0)
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return (nxt, outputs), None

    recv0 = jnp.zeros_like(x_micro[0])
    outs0 = jnp.zeros_like(x_micro)
    (recv, outputs), _ = jax.lax.scan(body, (recv0, outs0),
                                      jnp.arange(total_steps))
    return outputs


def pipeline_last_stage_value(x, axis_name: str = "pp"):
    """Broadcast the last stage's value to all pp ranks (sum works because
    other stages contribute zeros)."""
    return jax.lax.psum(x, axis_name)


def spmd_pipeline_1f1b(stage_fn: Callable, stage_params: Any, shared: Any,
                       first_fn: Callable, last_fn: Callable, n_micro: int,
                       axis_name: str = "pp", remat: bool = False):
    """True 1F1B microbatch schedule with manual backward (call inside
    shard_map).

    Reference parity: the SectionWorker 1F1B schedule
    (paddle/fluid/framework/section_worker.cc:144-180), where each stage
    interleaves one forward with one backward per slot so in-flight
    activations are bounded by the stage count rather than by the number
    of microbatches (F-then-B via ``spmd_pipeline`` + jax.grad keeps all
    ``n_micro`` activations live unless remat'd).

    SPMD lockstep formulation: all pp ranks run the same scan; at step t

      * stage ``s`` runs the FORWARD of microbatch ``t - s``;
      * stage ``s`` runs the BACKWARD of microbatch ``t - (2L-2-s)``
        (recompute-vjp from the stored stage input);

    both masked to their valid microbatch range. Activations are held in
    a circular buffer of ``2L-1`` slots — O(stages), independent of
    ``n_micro``. Two collective-permutes per step carry activations
    forward (+1) and output-grads backward (-1) around the pp ring.

    Args:
      stage_fn(stage_params, x) -> y: this device's stage (x/y same shape)
      shared: replicated params used by ``first_fn``/``last_fn``
      first_fn(shared, mb_idx) -> x: stage-0 input producer (e.g. embed)
      last_fn(shared, y, mb_idx) -> scalar loss contribution for one
        microbatch — scale by 1/n_micro inside so the sum is the mean
    Returns:
      (loss_sum, d_stage_params, d_shared) — loss/d_shared are partial
      per pp rank (stage-0 holds first_fn grads, last stage holds
      last_fn grads and the loss); psum over the pp axis to combine.
    """
    n_stages = _compat_axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    fn = jax.checkpoint(stage_fn, policy=_remat_policy()) \
        if remat else stage_fn
    total_steps = n_micro + 2 * (n_stages - 1)
    cap = 2 * n_stages - 1  # circular activation-store slots
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [((i + 1) % n_stages, i) for i in range(n_stages)]

    x0 = first_fn(shared, jnp.int32(0))
    zeros_x = jnp.zeros_like(x0)

    def body(carry, t):
        fwd_recv, bwd_recv, store, dp_acc, dsh_acc, loss_sum = carry

        # ---- forward tick: stage s, microbatch t - s -------------------
        mb_f = t - stage
        valid_f = (mb_f >= 0) & (mb_f < n_micro)
        mb_f_c = jnp.clip(mb_f, 0, n_micro - 1)
        x_in = jnp.where(stage == 0, first_fn(shared, mb_f_c), fwd_recv)
        slot_f = jnp.remainder(mb_f_c, cap)
        old = jax.lax.dynamic_index_in_dim(store, slot_f, keepdims=False)
        store = jax.lax.dynamic_update_index_in_dim(
            store, jnp.where(valid_f, x_in, old), slot_f, axis=0)
        y_out = fn(stage_params, x_in)

        # ---- backward tick: stage s, microbatch t - (2L-2-s) -----------
        mb_b = t - (2 * (n_stages - 1) - stage)
        valid_b = (mb_b >= 0) & (mb_b < n_micro)
        mb_b_c = jnp.clip(mb_b, 0, n_micro - 1)
        slot_b = jnp.remainder(mb_b_c, cap)
        x_saved = jax.lax.dynamic_index_in_dim(store, slot_b,
                                               keepdims=False)
        # last stage: seed grad from the loss of the microbatch whose
        # forward just finished here (mb_f == mb_b at the last stage)
        loss_mb, head_vjp = jax.vjp(
            lambda sh, yy: last_fn(sh, yy, mb_b_c), shared, y_out)
        dsh_head, dy_seed = head_vjp(jnp.ones_like(loss_mb))
        is_last = stage == n_stages - 1
        g_in = jnp.where(is_last, dy_seed, bwd_recv)
        _, stage_vjp = jax.vjp(fn, stage_params, x_saved)
        dp_mb, dx = stage_vjp(g_in)
        # stage 0: fold dx into first_fn (embed) grads per microbatch
        _, in_vjp = jax.vjp(lambda sh: first_fn(sh, mb_b_c), shared)
        (dsh_in,) = in_vjp(dx)

        mask = lambda flag, tree: jax.tree_util.tree_map(
            lambda g: jnp.where(flag, g, jnp.zeros_like(g)), tree)
        dp_acc = jax.tree_util.tree_map(
            jnp.add, dp_acc, mask(valid_b, dp_mb))
        dsh_acc = jax.tree_util.tree_map(
            jnp.add, dsh_acc,
            jax.tree_util.tree_map(
                jnp.add, mask(valid_b & is_last, dsh_head),
                mask(valid_b & (stage == 0), dsh_in)))
        loss_sum = loss_sum + jnp.where(valid_b & is_last, loss_mb, 0.0)

        # ---- ring hops (must run on every rank every step) -------------
        fwd_recv = jax.lax.ppermute(
            jnp.where(valid_f, y_out, jnp.zeros_like(y_out)),
            axis_name, fwd_perm)
        bwd_recv = jax.lax.ppermute(
            jnp.where(valid_b, dx, jnp.zeros_like(dx)),
            axis_name, bwd_perm)
        return (fwd_recv, bwd_recv, store, dp_acc, dsh_acc, loss_sum), None

    zeros_like_tree = functools.partial(jax.tree_util.tree_map,
                                        jnp.zeros_like)
    carry0 = (zeros_x, zeros_x,
              jnp.zeros((cap,) + x0.shape, x0.dtype),
              zeros_like_tree(stage_params), zeros_like_tree(shared),
              jnp.zeros((), jnp.float32))
    carry, _ = jax.lax.scan(body, carry0, jnp.arange(total_steps))
    _, _, _, dp_acc, dsh_acc, loss_sum = carry
    return loss_sum, dp_acc, dsh_acc
