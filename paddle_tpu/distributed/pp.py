"""Pipeline parallelism.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py (LayerDesc:44, SharedLayerDesc:62,
PipelineLayer:76) + pipeline_parallel.py train_batch micro-batch loop and
the C++ SectionWorker F-then-B / 1F1B schedules
(paddle/fluid/framework/section_worker.cc:130-180).

TPU-native design: a pipeline stage is a position along the "pp" mesh
axis. Inside ONE jitted SPMD program, ``spmd_pipeline`` runs the classic
collective-permute microbatch loop: every device applies ITS stage's
params each step and ppermutes activations to the next stage. jax.grad
through the loop reverses the permutes, yielding the F-then-B schedule;
XLA overlaps the permute hop with the next microbatch's compute. The
reference's send_v2/recv_v2 + per-microbatch scopes collapse into this
scan. (1F1B's memory profile comes from jax.checkpoint on the stage fn —
set remat=True.)
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn.container import LayerList


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py:44)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages, e.g. tied embeddings
    (reference: pp_layers.py:62; weight sync pp_layers.py:180-188)."""

    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Segments a LayerDesc list across pipeline stages
    (reference: pp_layers.py:76 PipelineLayer).

    Eager/forward semantics run the full stack (correct on any device
    count); the SPMD pipelined execution is built by ``spmd_pipeline``
    over the uniform block segment. ``seg_method="layer:<ClassName>"``
    marks which class forms the uniform pipelined body, as in the
    reference's "layer:TransformerBlock" convention.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, num_virtual_pipeline_stages=1):
        super().__init__()
        self.descs = list(layers)
        self.loss_fn = loss_fn
        self.num_stages = num_stages or 1
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval
        self.shared_layers = {}
        built: List[Layer] = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self.shared_layers:
                    built.append(self.shared_layers[d.layer_name])
                else:
                    layer = d.build_layer()
                    self.shared_layers[d.layer_name] = layer
                    built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # bare callable (e.g. lambda reshape)
                built.append(d)
        self.run_function = built
        self._layers = LayerList([b for b in built if isinstance(b, Layer)])

    def forward(self, x, **kwargs):
        for fn in self.run_function:
            x = fn(x)
        return x

    def get_stage_layers(self, stage: int, num_stages: Optional[int] = None
                         ) -> List:
        n = num_stages or self.num_stages
        per = (len(self.run_function) + n - 1) // n
        return self.run_function[stage * per:(stage + 1) * per]


def spmd_pipeline(stage_fn: Callable, stage_params: Any, x_micro,
                  axis_name: str = "pp", remat: bool = False):
    """Collective-permute pipeline over the pp mesh axis (call inside
    shard_map).

    stage_fn(params, x) -> y with matching x/y shapes; ``stage_params``
    are THIS device's stage weights (callers shard a stacked
    [n_stages, ...] pytree over the pp axis). x_micro: [n_micro, mb, ...]
    microbatched input (meaningful on stage 0; replicated elsewhere).
    Returns [n_micro, mb, ...] outputs valid on the LAST stage (zeros
    elsewhere); reduce with a pp-psum or mask as needed.
    """
    n_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total_steps = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(carry, t):
        recv_buf, outputs = carry
        mb_idx = jnp.clip(t - stage, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, jnp.clip(
            t, 0, n_micro - 1), keepdims=False)
        inp = jnp.where(stage == 0, first_in, recv_buf)
        out = fn(stage_params, inp)
        active = (t >= stage) & (t - stage < n_micro)
        out = jnp.where(active, out, jnp.zeros_like(out))
        # collect on the last stage
        is_last = stage == n_stages - 1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(active & is_last, out,
                      jax.lax.dynamic_index_in_dim(outputs, mb_idx,
                                                   keepdims=False)),
            mb_idx, axis=0)
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return (nxt, outputs), None

    recv0 = jnp.zeros_like(x_micro[0])
    outs0 = jnp.zeros_like(x_micro)
    (recv, outputs), _ = jax.lax.scan(body, (recv0, outs0),
                                      jnp.arange(total_steps))
    return outputs


def pipeline_last_stage_value(x, axis_name: str = "pp"):
    """Broadcast the last stage's value to all pp ranks (sum works because
    other stages contribute zeros)."""
    return jax.lax.psum(x, axis_name)
