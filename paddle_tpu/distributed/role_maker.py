"""Role makers: who am I in the job?

Reference parity: python/paddle/distributed/fleet/base/role_maker.py
(PaddleCloudRoleMaker parses the fleetrun env contract; UserDefinedRoleMaker
takes explicit ranks; Role enumerates WORKER/SERVER/HETER_WORKER).
"""

from __future__ import annotations

import os
from typing import List, Optional


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def _worker_index(self) -> int:
        raise NotImplementedError

    def _worker_num(self) -> int:
        raise NotImplementedError

    def _is_worker(self) -> bool:
        raise NotImplementedError

    def _is_server(self) -> bool:
        raise NotImplementedError

    def _is_first_worker(self) -> bool:
        return self._is_worker() and self._worker_index() == 0

    # reference public aliases
    def worker_index(self) -> int:
        return self._worker_index()

    def worker_num(self) -> int:
        return self._worker_num()

    def is_worker(self) -> bool:
        return self._is_worker()

    def is_server(self) -> bool:
        return self._is_server()

    def is_first_worker(self) -> bool:
        return self._is_first_worker()


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parses the launcher env contract (reference role_maker.py:946-area;
    contract set by distributed/launch.py: PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, TRAINING_ROLE, PADDLE_PORT/POD_IP for servers)."""

    def __init__(self, is_collective: bool = False, **kwargs):
        self._is_collective = is_collective
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in eps.split(",") if e]
        weps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in weps.split(",") if e]

    def _worker_index(self) -> int:
        return self._trainer_id

    def _worker_num(self) -> int:
        return self._trainers_num

    def _is_worker(self) -> bool:
        return self._role in ("TRAINER", "WORKER")

    def _is_server(self) -> bool:
        return self._role == "PSERVER"

    def _server_num(self) -> int:
        return len(self._server_endpoints)

    def get_pserver_endpoints(self) -> List[str]:
        return list(self._server_endpoints)

    def get_trainer_endpoints(self) -> List[str]:
        return list(self._worker_endpoints)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role configuration (reference: role_maker.py
    UserDefinedRoleMaker)."""

    def __init__(self, is_collective: bool = False,
                 current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1,
                 server_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__(is_collective)
        self._trainer_id = current_id
        self._trainers_num = worker_num
        self._role = "PSERVER" if role == Role.SERVER else "TRAINER"
        self._server_endpoints = server_endpoints or []
