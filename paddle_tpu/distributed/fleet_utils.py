"""fleet.utils compatibility namespace (reference:
python/paddle/distributed/fleet/utils/ — recompute and
hybrid-parallel gradient helpers)."""

from .parallel import recompute
from .fleet_util import UtilBase, fleet_util

__all__ = ["recompute", "UtilBase", "fleet_util",
           "fused_allreduce_gradients"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """reference: fleet/utils/hybrid_parallel_util.py:117
    fused_allreduce_gradients — dp-group grad sync for eager layers.
    Under the SPMD train step GSPMD inserts the reductions; this eager
    helper all-reduces .grad fields over the dp axis when tracing."""
    from .collective import all_reduce
    for p in parameter_list:
        if getattr(p, "grad", None) is not None:
            p.grad = all_reduce(p.grad, group="dp")
