"""paddle_tpu.distributed — collective API, mesh topology, fleet.

Reference parity: python/paddle/distributed/.
"""

from .env import (ParallelEnv, device_count, get_rank, get_world_size,
                  init_parallel_env, local_device_count)
