"""paddle_tpu.distributed — collective API, mesh topology, fleet.

Reference parity: python/paddle/distributed/.
"""

from .env import (ParallelEnv, device_count, get_rank, get_world_size,
                  init_parallel_env, local_device_count)

from . import collective
from .collective import (Group, ReduceOp, all_gather, all_gather_object,
                         all_reduce, alltoall, barrier, broadcast,
                         get_group, new_group, recv, reduce,
                         reduce_scatter, scatter, send, split, wait)
from .entry import CountFilterEntry, EntryAttr, ProbabilityEntry
from .spawn import spawn
from ..io.heavy_dataset import InMemoryDataset, QueueDataset
from .parallel import DataParallel, recompute
from .strategy import DistributedStrategy
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       create_hybrid_communicate_group,
                       get_hybrid_communicate_group, make_mesh)
from . import fleet, mp_layers, pp, sp
from .fleet_util import UtilBase, fleet_util
from .heter import DenseHostTable, HostEmbedding
from .localsgd import LocalSGDTrainStep
from .fault_inject import (FaultInjector, InjectedFault, fault_point,
                           get_injector)
from .resilience import (HeartbeatMonitor, ResilientCheckpointManager,
                         ResilientTrainer, RetryExhausted, RetryPolicy,
                         call_with_retry, get_retry_policy,
                         set_site_policy)
