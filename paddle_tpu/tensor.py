"""Eager Tensor: a jax.Array wrapper carrying autograd/tape state.

TPU-native equivalent of the reference's VarBase/VariableWrapper
(reference: paddle/fluid/imperative/layer.h VarBase,
imperative/variable_wrapper.h; Python-side patching
python/paddle/fluid/dygraph/varbase_patch_methods.py). The wrapped value may
be a concrete device array (eager) or a jax tracer (inside functional
capture) — ops unwrap either transparently.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.dtype import convert_dtype


class Tensor:
    __slots__ = ("value", "stop_gradient", "grad", "grad_node", "_out_index",
                 "name", "persistable", "_retain_grads", "_grad_hooks",
                 "_inplace_version", "is_distributed", "pspec",
                 "__weakref__")

    def __init__(self, value, stop_gradient: bool = True,
                 name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value.value
        self.value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self.grad_node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._retain_grads = False
        self._grad_hooks: List[Any] = []
        self._inplace_version = 0

    # -- array protocol ------------------------------------------------------

    def __jax_array__(self):
        return self.value

    def __array__(self, dtype=None):
        arr = np.asarray(self.value)
        return arr.astype(dtype) if dtype is not None else arr

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def size(self):
        return int(np.prod(self.value.shape)) if self.value.shape else 1

    @property
    def T(self):
        from . import dispatch
        return dispatch.apply("t", self)

    @property
    def is_leaf(self) -> bool:
        return self.grad_node is None

    @property
    def place(self):
        from .core.place import expected_place
        devs = getattr(self.value, "devices", None)
        return expected_place()

    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    def item(self):
        return np.asarray(self.value).item()

    def tolist(self):
        return np.asarray(self.value).tolist()

    def detach(self) -> "Tensor":
        t = Tensor(self.value, stop_gradient=True, name=self.name)
        return t

    def clone(self) -> "Tensor":
        from . import dispatch
        return dispatch.apply("clone", self)

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return jnp.dtype(self.dtype).itemsize

    def astype(self, dtype) -> "Tensor":
        from . import dispatch
        return dispatch.apply("cast", self, dtype)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(
            self.value, jax.devices("cpu")[0]), self.stop_gradient)

    def to(self, *args, **kwargs) -> "Tensor":
        if args and isinstance(args[0], (str, np.dtype)) and str(
                args[0]).lower() not in ("cpu", "tpu", "gpu"):
            return self.astype(args[0])
        return self

    def block_until_ready(self) -> "Tensor":
        if hasattr(self.value, "block_until_ready"):
            self.value.block_until_ready()
        return self

    # -- autograd ------------------------------------------------------------

    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        from .autograd.engine import backward as _backward
        _backward(self, grad_tensor, retain_graph=retain_graph)

    def retain_grads(self) -> None:
        self._retain_grads = True

    def register_hook(self, hook) -> None:
        self._grad_hooks.append(hook)

    def clear_grad(self) -> None:
        self.grad = None

    def clear_gradient(self) -> None:
        self.grad = None

    def _accumulate_grad(self, g) -> None:
        g = g.value if isinstance(g, Tensor) else g
        if self.grad is None:
            self.grad = Tensor(jnp.asarray(g), stop_gradient=True,
                               name=(self.name or "") + "@GRAD")
        else:
            self.grad = Tensor(self.grad.value + g, stop_gradient=True,
                               name=self.grad.name)

    # -- in-place-style helpers (functional under the hood) -------------------

    def _inplace_assign(self, new: "Tensor") -> "Tensor":
        self.value = new.value if isinstance(new, Tensor) else new
        if isinstance(new, Tensor):
            self.grad_node = new.grad_node
            self._out_index = new._out_index
        self._inplace_version += 1
        return self

    def set_value(self, value) -> None:
        value = value.value if isinstance(value, Tensor) else jnp.asarray(
            value)
        self.value = value.astype(self.dtype) if value.dtype != self.dtype \
            else value
        self._inplace_version += 1

    def fill_(self, v) -> "Tensor":
        self.value = jnp.full_like(self.value, v)
        self._inplace_version += 1
        return self

    def zero_(self) -> "Tensor":
        self.value = jnp.zeros_like(self.value)
        self._inplace_version += 1
        return self

    def scale_(self, v) -> "Tensor":
        self.value = self.value * v
        self._inplace_version += 1
        return self

    def add_(self, other) -> "Tensor":
        other = other.value if isinstance(other, Tensor) else other
        self.value = self.value + other
        self._inplace_version += 1
        return self

    def subtract_(self, other) -> "Tensor":
        other = other.value if isinstance(other, Tensor) else other
        self.value = self.value - other
        self._inplace_version += 1
        return self

    def _inplace_op(self, name: str, *args, **kwargs) -> "Tensor":
        from . import dispatch
        return self._inplace_assign(dispatch.apply(name, self, *args,
                                                   **kwargs))

    def reshape_(self, shape) -> "Tensor":
        return self._inplace_op("reshape", shape)

    def squeeze_(self, axis=None) -> "Tensor":
        return self._inplace_op("squeeze", axis)

    def unsqueeze_(self, axis) -> "Tensor":
        return self._inplace_op("unsqueeze", axis)

    def scatter_(self, index, updates, overwrite: bool = True) -> "Tensor":
        return self._inplace_op("scatter", index, updates, overwrite)

    def tanh_(self) -> "Tensor":
        return self._inplace_op("tanh")

    def ceil_(self) -> "Tensor":
        return self._inplace_op("ceil")

    def floor_(self) -> "Tensor":
        return self._inplace_op("floor")

    def round_(self) -> "Tensor":
        return self._inplace_op("round")

    def exp_(self) -> "Tensor":
        return self._inplace_op("exp")

    def sqrt_(self) -> "Tensor":
        return self._inplace_op("sqrt")

    def rsqrt_(self) -> "Tensor":
        return self._inplace_op("rsqrt")

    def reciprocal_(self) -> "Tensor":
        return self._inplace_op("reciprocal")

    def clip_(self, min=None, max=None) -> "Tensor":  # noqa: A002
        return self._inplace_op("clip", min, max)

    def flatten_(self, start_axis: int = 0,
                 stop_axis: int = -1) -> "Tensor":
        return self._inplace_op("flatten", start_axis, stop_axis)

    def gradient(self):
        """Legacy accessor (reference: varbase_patch_methods.py
        gradient()) — the accumulated grad as a numpy array, or None."""
        if self.grad is None:
            return None
        return np.asarray(self.grad.value)

    @property
    def inplace_version(self) -> int:
        """reference: Tensor.inplace_version — bumped on each in-place
        write (used by autograd safety checks there; informational
        here since in-place ops are functional underneath)."""
        return self._inplace_version

    @property
    def block(self):
        """reference: Tensor.block (the owning program block). The
        traced world has no block under construction; returns the
        current default program when one is active, else the global
        startup-program holder so attribute access never lands on
        None."""
        from .static.api import default_startup_program
        from .static.program import default_main_program
        return default_main_program() or default_startup_program()

    def where(self, x, y) -> "Tensor":
        """reference: Tensor.where(x, y) — self is the bool condition."""
        from . import dispatch
        return dispatch.apply("where", self, x, y)

    # -- python protocol ------------------------------------------------------

    def __len__(self) -> int:
        if not self.shape:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __bool__(self) -> bool:
        return bool(np.asarray(self.value))

    def __int__(self) -> int:
        return int(np.asarray(self.value))

    def __float__(self) -> float:
        return float(np.asarray(self.value))

    def __index__(self) -> int:
        return int(np.asarray(self.value))

    def __repr__(self) -> str:
        sg = self.stop_gradient
        return (f"Tensor(shape={list(self.shape)}, dtype={self.dtype}, "
                f"stop_gradient={sg},\n{np.asarray(self.value)})")

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        from . import dispatch
        return dispatch.getitem(self, idx)

    def __setitem__(self, idx, value):
        from . import dispatch
        dispatch.setitem(self, idx, value)

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by paddle_tpu.dispatch.monkey_patch()


class Parameter(Tensor):
    """Trainable tensor (reference: framework.py Parameter; dygraph params
    default to stop_gradient=False)."""

    # is_distributed/pspec storage lives on Tensor so BUFFERS (e.g. an
    # int8 weight after weight-only conversion) can carry sharding too
    __slots__ = ("trainable", "optimize_attr", "regularizer",
                 "do_model_average", "need_clip")

    def __init__(self, value, name: Optional[str] = None,
                 trainable: bool = True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False
        self.pspec = None  # PartitionSpec for pjit-sharded training

    @property
    def requires_grad(self) -> bool:
        return not self.stop_gradient


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True
              ) -> Tensor:
    """paddle.to_tensor equivalent."""
    if isinstance(data, Tensor):
        val = data.value
        if dtype is not None:
            val = val.astype(convert_dtype(dtype))
        return Tensor(val, stop_gradient=stop_gradient)
    from .ops.creation import to_array
    return Tensor(to_array(data, dtype), stop_gradient=stop_gradient)
