"""RNG state management.

TPU-native equivalent of the reference's global Generator plus the
hybrid-parallel RNG tracker (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:24
RNGStatesTracker; python/paddle/framework/random.py seed handling). Eager
mode holds a mutable key that is split per draw; named states give
per-mesh-axis streams (e.g. identical dropout inside a TP group, distinct
across DP ranks).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict

import jax

from .enforce import AlreadyExistsError, NotFoundError
from .flags import get_flag


class Generator:
    """A mutable PRNG stream over a functional jax key. Key creation is
    lazy so importing the framework never forces backend initialization
    (TPU runtime bring-up can be slow)."""

    def __init__(self, seed: int = 0):
        self._key = None
        self._seed = seed
        self._lock = threading.Lock()

    def seed(self, seed: int) -> None:
        with self._lock:
            self._key = jax.random.key(seed)
            self._seed = seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            return self._key

    def set_state(self, key) -> None:
        with self._lock:
            self._key = key

    @property
    def initial_seed(self) -> int:
        return self._seed


_DEFAULT = Generator(0)
_seeded = False


def default_generator() -> Generator:
    global _seeded
    if not _seeded:
        _DEFAULT.seed(int(get_flag("seed")))
        _seeded = True
    return _DEFAULT


def seed(seed: int) -> Generator:
    global _seeded
    _seeded = True
    _DEFAULT.seed(int(seed))
    return _DEFAULT


def next_key():
    return default_generator().next_key()


class RNGStatesTracker:
    """Named independent RNG streams for hybrid parallelism."""

    def __init__(self) -> None:
        self._states: Dict[str, Generator] = {}

    def add(self, name: str, seed: int) -> None:
        if name in self._states:
            raise AlreadyExistsError(f"RNG state {name!r} already exists")
        self._states[name] = Generator(seed)

    def reset(self) -> None:
        self._states.clear()

    @contextlib.contextmanager
    def rng_state(self, name: str):
        """Temporarily make the named stream the default generator."""
        if name not in self._states:
            raise NotFoundError(f"RNG state {name!r} not registered")
        global _DEFAULT
        prev = _DEFAULT
        _DEFAULT = self._states[name]
        try:
            yield
        finally:
            _DEFAULT = prev


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER


@contextlib.contextmanager
def key_scope(key):
    """Route next_key() draws through ``key`` (may be a tracer) — used by
    functional capture so dropout keys are jit arguments, not baked-in
    constants."""
    global _DEFAULT, _seeded
    prev, prev_seeded = _DEFAULT, _seeded
    gen = Generator.__new__(Generator)
    gen._key = key
    gen._seed = -1
    import threading as _t
    gen._lock = _t.Lock()
    _DEFAULT = gen
    _seeded = True
    try:
        yield gen
    finally:
        _DEFAULT, _seeded = prev, prev_seeded
