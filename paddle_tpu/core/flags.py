"""Global flags registry.

TPU-native equivalent of the reference's gflags runtime-knob system
(reference: paddle/fluid/platform/flags.cc:33-603, exposed to Python via
paddle/fluid/pybind/global_value_getter_setter.cc). Flags are plain Python
values with env-var overrides (``PT_FLAGS_<name>`` or legacy
``FLAGS_<name>``), settable at runtime via :func:`set_flags`.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _FlagInfo:
    name: str
    default: Any
    value: Any
    doc: str
    parser: Callable[[str], Any]


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class FlagRegistry:
    """Thread-safe named-flag registry with env overrides."""

    def __init__(self) -> None:
        self._flags: Dict[str, _FlagInfo] = {}
        self._lock = threading.RLock()

    def define(self, name: str, default: Any, doc: str = "") -> None:
        ty = type(default)
        if ty is bool:
            parser: Callable[[str], Any] = _parse_bool
        elif ty is int:
            parser = int
        elif ty is float:
            parser = float
        else:
            parser = str
        value = default
        for env_key in (f"PT_FLAGS_{name}", f"FLAGS_{name}"):
            if env_key in os.environ:
                value = parser(os.environ[env_key])
                break
        with self._lock:
            self._flags[name] = _FlagInfo(name, default, value, doc, parser)

    def get(self, name: str) -> Any:
        with self._lock:
            try:
                return self._flags[name].value
            except KeyError:
                raise KeyError(f"Unknown flag {name!r}") from None

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"Unknown flag {name!r}")
            info = self._flags[name]
            if isinstance(value, str) and not isinstance(info.default, str):
                value = info.parser(value)
            info.value = value

    def reset(self, name: Optional[str] = None) -> None:
        with self._lock:
            names = [name] if name else list(self._flags)
            for n in names:
                self._flags[n].value = self._flags[n].default

    def all(self) -> Dict[str, Any]:
        with self._lock:
            return {k: v.value for k, v in self._flags.items()}


GLOBAL_FLAGS = FlagRegistry()


def define_flag(name: str, default: Any, doc: str = "") -> None:
    GLOBAL_FLAGS.define(name, default, doc)


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    return {n: GLOBAL_FLAGS.get(n) for n in names}


def get_flag(name: str) -> Any:
    return GLOBAL_FLAGS.get(name)


def set_flags(flags: Dict[str, Any]) -> None:
    for k, v in flags.items():
        GLOBAL_FLAGS.set(k, v)


# Core runtime knobs (analogs of the reference's most-used FLAGS_*).
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf after each eager op.")
define_flag("benchmark", False, "Block-until-ready and time each eager op.")
define_flag("eager_jit_cache", True, "Cache jitted computations for eager op dispatch.")
define_flag("default_dtype", "float32", "Default floating dtype for new tensors.")
define_flag("amp_dtype", "bfloat16", "Autocast low-precision dtype (bf16 first-class on TPU).")
define_flag("profiler_enabled", False, "Collect RecordEvent host events.")
define_flag("log_level", 0, "Verbose log level (higher = chattier).")
define_flag("seed", 0, "Global RNG seed when not set explicitly.")
define_flag("fuse_optimizer", False,
            "Run optimizer updates on one concatenated flat buffer per "
            "dtype group (analog of the reference's fused-optimizer IR "
            "passes). Fewer kernels but extra concat/split copies - wins "
            "only when per-kernel overhead dominates copy bandwidth.")
