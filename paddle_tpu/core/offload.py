"""Process-global activation-offload switch.

Reference parity: recompute_configs.enable_offload
(fleet/meta_optimizers/recompute_optimizer + offload_helper) moves
checkpointed activations to host memory. TPU-native: the rematerialized
blocks' jax.checkpoint calls adopt an offload policy — saved dot results
stage to pinned host memory during forward and stream back in backward.
The switch is process-global, mirroring the reference's global FLAGS_*
style; it is consulted at trace time by the remat wrappers
(models/gpt.py _remat_block and nn layers using jax.checkpoint).
"""

from __future__ import annotations

_activation_offload = False


def set_activation_offload(enabled: bool) -> None:
    global _activation_offload
    _activation_offload = bool(enabled)


def activation_offload_enabled() -> bool:
    return _activation_offload


def remat_policy():
    """The jax.checkpoint policy to use for rematerialized blocks (None
    = plain full-remat). With offload on, the named block inputs — the
    only residuals a fully-rematerialized block keeps — are staged to
    pinned host memory (the reference's recompute offload stashes
    exactly these checkpoint inputs on host)."""
    if not _activation_offload:
        return None
    import jax
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=["remat_block_in"],
        offload_src="device", offload_dst="pinned_host")


def name_block_input(x):
    """Tag a rematerialized block's input so the offload policy can
    target it (no-op data-wise)."""
    if not _activation_offload:
        return x
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, "remat_block_in")
