"""Process-global activation-offload switch.

Reference parity: recompute_configs.enable_offload
(fleet/meta_optimizers/recompute_optimizer + offload_helper) moves
checkpointed activations to host memory. TPU-native: the rematerialized
blocks' jax.checkpoint calls adopt an offload policy — saved dot results
stage to pinned host memory during forward and stream back in backward.
The switch is process-global, mirroring the reference's global FLAGS_*
style; it is consulted at trace time by the remat wrappers
(models/gpt.py _remat_block and nn layers using jax.checkpoint).
"""

from __future__ import annotations

import contextlib as _contextlib

_activation_offload = False

# The one named activation currently defined: the flash attention
# kernel's out+lse backward residuals (tagged in
# ops/pallas/flash_attention._flash_lse_vjp_fwd).
ATTN_OUT_NAME = "attn_out"

# Named activations that rematerialized blocks SAVE instead of
# recomputing (selective checkpointing): e.g. (ATTN_OUT_NAME,) keeps
# each attention mix's output — at long sequence the flash forward is
# the block's most expensive piece, and its output is only [B, S, H]
# per layer, so buying it back costs little memory.
_remat_saved_names: tuple = ()


def set_activation_offload(enabled: bool) -> None:
    global _activation_offload
    _activation_offload = bool(enabled)


def activation_offload_enabled() -> bool:
    return _activation_offload


def set_remat_saved_names(names) -> None:
    """Select named activations (see ``name_activation``) that
    jax.checkpoint saves rather than recomputes inside remat blocks."""
    global _remat_saved_names
    _remat_saved_names = tuple(names)


@_contextlib.contextmanager
def override_remat_saved_names(names):
    """Scoped selection: a model that opted into selective remat wraps
    its forward trace in this, so its choice never leaks into other
    live models' traces (r4 advisor: GPTModel.__init__ used to clobber
    the process global for models that never opted in). Nesting
    restores the previous selection on exit."""
    global _remat_saved_names
    prev = _remat_saved_names
    _remat_saved_names = tuple(names)
    try:
        yield
    finally:
        _remat_saved_names = prev


def remat_saved_names() -> tuple:
    return _remat_saved_names


def remat_policy():
    """The jax.checkpoint policy to use for rematerialized blocks (None
    = plain full-remat). With offload on, the named block inputs — the
    only residuals a fully-rematerialized block keeps — are staged to
    pinned host memory (the reference's recompute offload stashes
    exactly these checkpoint inputs on host). Named saved activations
    (set_remat_saved_names) are kept on device in both modes."""
    import jax
    if _activation_offload:
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=list(_remat_saved_names),
            names_which_can_be_offloaded=["remat_block_in"],
            offload_src="device", offload_dst="pinned_host")
    if _remat_saved_names:
        return jax.checkpoint_policies.save_only_these_names(
            *_remat_saved_names)
    return None


def name_block_input(x):
    """Tag a rematerialized block's input so the offload policy can
    target it (no-op data-wise)."""
    if not _activation_offload:
        return x
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, "remat_block_in")


def name_activation(x, name: str):
    """Tag a named activation for selective remat saving (no-op unless
    ``name`` is currently selected via set_remat_saved_names)."""
    if name not in _remat_saved_names:
        return x
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, name)
