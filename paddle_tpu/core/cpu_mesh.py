"""Multi-device CPU subprocess harness for mesh tests and benches.

The tensor-parallel serving stack (r10) is validated on a CPU
host-platform mesh: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
turns one CPU into N fake XLA devices, which exercises the full GSPMD
path — NamedSharding placement, shard_map dispatch, collective
insertion — with bit-exact arithmetic and no TPU in the loop.

The flag only takes effect BEFORE the first backend initialization, so
a process that already imported jax cannot flip its device count. This
module is the clean-room answer: run the mesh payload in a FRESH
subprocess with the flag (and ``JAX_PLATFORMS=cpu``) pinned in its
environment. That keeps single-device callers (bench_all's main
process, a user REPL, any test file that assumes one device) untouched
— the PR-1 lesson that leaked multi-device state poisons every later
test in the process.

The tier-1 suite's own conftest already forces an 8-device host
platform for everything under ``tests/``, so test code MAY build
serving meshes in-process there; the subprocess runner is for (a)
payloads that must not inherit the parent's jax state, (b) bench
entries driven from arbitrary environments, and (c) pinning that the
flag-plumbing itself works from a cold start.

Protocol: the payload prints its result as one JSON document on a
sentinel-marked line (``emit_result`` below, importable in the child);
``run_cpu_mesh_json`` returns the parsed object and raises with the
child's full output on any failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any, Dict, Optional

__all__ = ["DEVICE_FLAG", "cpu_mesh_env", "run_cpu_mesh_subprocess",
           "run_cpu_mesh_json", "emit_result", "RESULT_SENTINEL"]

DEVICE_FLAG = "--xla_force_host_platform_device_count"
RESULT_SENTINEL = "CPU_MESH_RESULT:"


def cpu_mesh_env(device_count: int = 8,
                 extra_env: Optional[Dict[str, str]] = None
                 ) -> Dict[str, str]:
    """Child environment: inherited env with the host-platform device
    flag appended to XLA_FLAGS (any existing device-count flag is
    dropped — last-one-wins is backend-dependent, explicit is safer),
    ``JAX_PLATFORMS=cpu`` pinned, and the repo root on PYTHONPATH so a
    bare ``python -c`` child can import paddle_tpu."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(DEVICE_FLAG)]
    flags.append(f"{DEVICE_FLAG}={int(device_count)}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pp = env.get("PYTHONPATH", "")
    if repo_root not in pp.split(os.pathsep):
        env["PYTHONPATH"] = (repo_root + os.pathsep + pp) if pp \
            else repo_root
    if extra_env:
        env.update(extra_env)
    return env


def run_cpu_mesh_subprocess(source: str, device_count: int = 8,
                            extra_env: Optional[Dict[str, str]] = None,
                            timeout_s: float = 600.0
                            ) -> "subprocess.CompletedProcess":
    """Execute ``source`` (python code) in a fresh interpreter under an
    N-fake-device CPU host platform. Raises RuntimeError with the
    child's combined output when it exits non-zero (subprocess
    tracebacks must surface in the pytest report, not vanish)."""
    proc = subprocess.run(
        [sys.executable, "-c", source],
        env=cpu_mesh_env(device_count, extra_env),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout_s)
    if proc.returncode != 0:
        raise RuntimeError(
            f"cpu-mesh subprocess failed (rc={proc.returncode}):\n"
            f"{proc.stdout}")
    return proc


def run_cpu_mesh_json(source: str, device_count: int = 8,
                      extra_env: Optional[Dict[str, str]] = None,
                      timeout_s: float = 600.0) -> Any:
    """`run_cpu_mesh_subprocess` + parse the child's ``emit_result``
    payload (the LAST sentinel line wins, so stray child logging above
    it is harmless)."""
    proc = run_cpu_mesh_subprocess(source, device_count, extra_env,
                                   timeout_s)
    payload = None
    for line in proc.stdout.splitlines():
        if line.startswith(RESULT_SENTINEL):
            payload = line[len(RESULT_SENTINEL):].strip()
    if payload is None:
        raise RuntimeError(
            f"cpu-mesh subprocess printed no {RESULT_SENTINEL!r} line:"
            f"\n{proc.stdout}")
    return json.loads(payload)


def emit_result(obj: Any) -> None:
    """Child-side half of the protocol: print ``obj`` as the sentinel
    line `run_cpu_mesh_json` parses."""
    print(RESULT_SENTINEL, json.dumps(obj), flush=True)
