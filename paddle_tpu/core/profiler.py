"""Profiling: RecordEvent markers + jax.profiler integration.

TPU-native equivalent of the reference's profiler
(reference: paddle/fluid/platform/profiler.h:127 RecordEvent,
:213 EnableProfiler; device events via CUPTI device_tracer.h:43). Host
events are collected in-process; device-side tracing delegates to
``jax.profiler`` (XLA/TPU trace → TensorBoard), and every RecordEvent also
opens a ``jax.named_scope`` so markers show up inside XLA traces.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax

from .flags import get_flag, set_flags


@dataclass
class _Event:
    name: str
    start_us: float
    end_us: float
    thread_id: int
    annotation: Optional[str] = None


@dataclass
class _ProfilerState:
    enabled: bool = False
    events: List[_Event] = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)


_STATE = _ProfilerState()


class RecordEvent:
    """RAII host-event marker; nests a jax.named_scope for device traces."""

    def __init__(self, name: str, annotation: Optional[str] = None):
        self.name = name
        self.annotation = annotation
        self._scope = None
        self._start = 0.0

    def __enter__(self):
        self._start = time.perf_counter() * 1e6
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        self._scope.__exit__(*exc)
        if _STATE.enabled or get_flag("profiler_enabled"):
            evt = _Event(self.name, self._start, time.perf_counter() * 1e6,
                         threading.get_ident(), self.annotation)
            with _STATE.lock:
                _STATE.events.append(evt)
        return False


def enable_profiler() -> None:
    set_flags({"profiler_enabled": True})
    _STATE.enabled = True
    with _STATE.lock:
        _STATE.events.clear()


def disable_profiler() -> None:
    set_flags({"profiler_enabled": False})
    _STATE.enabled = False


def reset_profiler() -> None:
    with _STATE.lock:
        _STATE.events.clear()


def profiler_events() -> List[_Event]:
    with _STATE.lock:
        return list(_STATE.events)


def profiler_active() -> bool:
    """Cheap enabled-check for external event sources (the serving
    span tracer bridges through this before paying any work)."""
    return _STATE.enabled or bool(get_flag("profiler_enabled"))


def external_event(name: str, start_us: float, end_us: float,
                   annotation: Optional[str] = None) -> None:
    """Inject an externally-timed host event (perf_counter/monotonic
    microseconds — the same clock domain on Linux). The serving span
    tracer (serving/tracing.py) uses this so request spans land in the
    same ``export_chrome_trace`` as RecordEvent markers."""
    if not profiler_active():
        return
    evt = _Event(name, float(start_us), float(end_us),
                 threading.get_ident(), annotation)
    with _STATE.lock:
        _STATE.events.append(evt)


def export_chrome_trace(path: str) -> None:
    """Write collected host events as a chrome://tracing JSON file."""
    with _STATE.lock:
        events = list(_STATE.events)
    trace = {"traceEvents": [
        {"name": e.name, "ph": "X", "ts": e.start_us,
         "dur": max(e.end_us - e.start_us, 0.01), "pid": 0,
         "tid": e.thread_id % 1_000_000,
         "args": ({"annotation": e.annotation} if e.annotation else {})}
        for e in events]}
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler_guard(trace_dir: Optional[str] = None):
    """Context manager enabling host events and optional XLA device trace."""
    enable_profiler()
    if trace_dir is not None:
        jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        if trace_dir is not None:
            jax.profiler.stop_trace()
        disable_profiler()
