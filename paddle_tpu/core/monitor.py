"""Global named-stat registry.

TPU-native equivalent of the reference's monitoring counters
(reference: paddle/fluid/platform/monitor.h:34-120 StatValue/StatRegistry).
"""

from __future__ import annotations

import threading
from typing import Dict


class StatValue:
    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, delta: int = 1) -> int:
        with self._lock:
            self._value += delta
            return self._value

    def set(self, value: int) -> None:
        with self._lock:
            self._value = int(value)

    def get(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        self.set(0)


class StatRegistry:
    def __init__(self) -> None:
        self._stats: Dict[str, StatValue] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> StatValue:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = StatValue(name)
            return self._stats[name]

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: v.get() for k, v in self._stats.items()}

    def reset_all(self) -> None:
        with self._lock:
            for v in self._stats.values():
                v.reset()


GLOBAL_STATS = StatRegistry()


def stat(name: str) -> StatValue:
    return GLOBAL_STATS.get(name)
