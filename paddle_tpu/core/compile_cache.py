"""Env-gated JAX persistent compilation cache.

``PADDLE_TPU_COMPILE_CACHE=<dir>`` points every process at a shared
on-disk cache of compiled XLA executables: a restarted serving engine
(or a bench re-run) re-reads its prefill/decode/verify programs
instead of recompiling them — and, on the tunneled dev runtime, a
cached compile never touches the remote-compile transport at all,
which is the workaround lane for the 1.3B int8 whole-program compile
that reproducibly kills that transport (BENCH_STAGED.json decode/
int8_weight_only, VERDICT weak #3).

Call sites: `ContinuousBatchingEngine.__init__` (the serving engine's
construction path) and `bench_all.main` (the staged sweep). Explicit
``enable_compile_cache(path)`` wins over the env var; with neither,
this is a no-op — the cache is strictly opt-in because a shared dir
across incompatible jax/backend versions is the user's call to make.

The min-entry-size / min-compile-time thresholds are dropped to zero
so CPU-smoke-scale programs cache too (the defaults only persist
multi-second compiles); older jax spellings of those knobs are
tolerated by skipping what the installed version lacks.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["enable_compile_cache", "disable_compile_cache",
           "compile_cache_dir", "ENV_VAR"]

ENV_VAR = "PADDLE_TPU_COMPILE_CACHE"

_enabled_dir: Optional[str] = None


def compile_cache_dir() -> Optional[str]:
    """The directory the cache was enabled with (None = off)."""
    return _enabled_dir


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Idempotently point jax's persistent compilation cache at
    ``path`` (default: $PADDLE_TPU_COMPILE_CACHE; unset/empty = no-op).
    Returns the active cache dir, or None when disabled."""
    global _enabled_dir
    if path is None:
        path = os.environ.get(ENV_VAR, "").strip() or None
    if path is None:
        return _enabled_dir
    path = os.path.abspath(path)
    if _enabled_dir == path:
        return _enabled_dir
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # jax memoizes "no cache configured" at the FIRST compile of the
    # process; enabling after any jit has run needs the memo dropped
    # or the new dir is silently ignored
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:
        pass
    for flag, val in (
            # persist everything: the engine's CPU-lane programs are
            # small and fast to compile but still worth skipping, and
            # the flags exist precisely to opt into that
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            # newer jax gates non-TPU backends behind an explicit
            # enable; older versions don't have the flag
            ("jax_persistent_cache_enable_xla_caches", "all")):
        try:
            jax.config.update(flag, val)
        except (AttributeError, ValueError):
            pass
    _enabled_dir = path
    return _enabled_dir


def disable_compile_cache() -> None:
    """Fully detach jax from the enabled cache dir: config reset AND
    the memoized cache object dropped, so later compiles neither read
    from nor write to a dir that may be gone (bench A/B hygiene — a
    dangling config pointing at a deleted temp dir would warn on every
    compile for the rest of the process). ``enable_compile_cache``
    re-attaches."""
    global _enabled_dir
    if _enabled_dir is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:
        pass
    _enabled_dir = None
