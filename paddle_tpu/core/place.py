"""Device/place model over the PJRT runtime.

TPU-native equivalent of the reference's Place variants + DeviceContextPool
(reference: paddle/fluid/platform/place.h:26-75,
platform/device_context.h). On the XLA stack a "place" maps to a
``jax.Device``; streams/contexts are owned by the runtime, so this layer is a
thin, cached facade used by tensor factories and the data loader.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax


class Place:
    """A logical device slot: backend platform + device index."""

    platform: str = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Place) and self.platform == other.platform
                and self.device_id == other.device_id)

    def __hash__(self) -> int:
        return hash((self.platform, self.device_id))

    def __repr__(self) -> str:
        return f"Place({self.platform}:{self.device_id})"

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self.platform]
        if not devs:  # fall back: requested platform absent (e.g. TPU on CI)
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    platform = "cpu"


class TPUPlace(Place):
    platform = "tpu"


class GPUPlace(Place):
    platform = "gpu"


# Alias matching the reference's naming for CUDA places.
CUDAPlace = GPUPlace


class CUDAPinnedPlace(Place):
    """reference: platform/place.h CUDAPinnedPlace — page-locked host
    staging memory. On TPU, host staging is managed by PJRT; this place is
    accepted by the API surface and maps to host memory."""
    platform = "cpu"

    def __init__(self):
        super().__init__(0)


class NPUPlace(Place):
    """reference: platform/place.h NPUPlace (Ascend). Accepted for API
    parity; resolves to the default accelerator platform if present."""
    platform = "tpu"


class XPUPlace(Place):
    """reference: platform/place.h XPUPlace (Kunlun). Accepted for API
    parity; resolves to the default accelerator platform if present."""
    platform = "tpu"


@functools.lru_cache(maxsize=None)
def _default_place() -> Place:
    plat = jax.default_backend()
    if plat == "tpu":
        return TPUPlace(0)
    if plat == "gpu":
        return GPUPlace(0)
    return CPUPlace(0)


_expected_place: Optional[Place] = None


def set_device(device: Union[str, Place]) -> Place:
    """Set the global expected place, e.g. ``set_device('tpu:0')``."""
    global _expected_place
    if isinstance(device, Place):
        _expected_place = device
        return device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    cls = {"cpu": CPUPlace, "tpu": TPUPlace, "gpu": GPUPlace,
           "cuda": GPUPlace}.get(name.lower())
    if cls is None:
        from .enforce import InvalidArgumentError
        raise InvalidArgumentError(f"Unknown device {device!r}")
    _expected_place = cls(idx)
    return _expected_place


def get_device() -> str:
    p = expected_place()
    return f"{p.platform}:{p.device_id}"


def expected_place() -> Place:
    return _expected_place if _expected_place is not None else _default_place()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def device_count(platform: Optional[str] = None) -> int:
    if platform is None:
        platform = expected_place().platform
    return len([d for d in jax.devices() if d.platform == platform]) or 1
