"""Dtype system — bf16 first-class.

TPU-native equivalent of the reference's VarType dtype enum
(reference: paddle/fluid/framework/framework.proto VarType, and
python/paddle/fluid/data_feeder.py convert_dtype). Canonical dtypes are
numpy/jax dtypes; strings and numpy types normalize through
:func:`convert_dtype`.
"""

from __future__ import annotations

from typing import Any, Union

import jax.numpy as jnp
import numpy as np

from .flags import get_flag

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bf16": "bfloat16",
    "fp16": "float16",
    "half": "float16",
    "fp32": "float32",
    "float": "float32",
    "fp64": "float64",
    "double": "float64",
    "bool": "bool_",
    "int": "int32",
    "long": "int64",
}

_NAME_TO_DTYPE = {
    "bfloat16": bfloat16, "float16": float16, "float32": float32,
    "float64": float64, "int8": int8, "int16": int16, "int32": int32,
    "int64": int64, "uint8": uint8, "uint16": uint16, "uint32": uint32,
    "bool_": bool_, "complex64": complex64,
}

DTypeLike = Union[str, np.dtype, type, Any]


_64BIT_CANON = {"int64": "int32", "uint64": "uint32", "float64": "float32",
                "complex128": "complex64"}


def convert_dtype(dtype: DTypeLike):
    """Normalize any dtype spelling to a jax/numpy dtype object.

    TPU-native canonicalization: in x32 mode (the default; 64-bit types are
    not TPU-performant) 64-bit dtypes map to their 32-bit counterparts, so
    reference-API calls asking for int64 indices run natively."""
    if dtype is None:
        return default_dtype()
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        d = jnp.dtype(_NAME_TO_DTYPE[name]) if name in _NAME_TO_DTYPE \
            else jnp.dtype(name)
    else:
        d = jnp.dtype(dtype)
    import jax
    if not jax.config.jax_enable_x64 and d.name in _64BIT_CANON:
        d = jnp.dtype(_64BIT_CANON[d.name])
    return d


def default_dtype():
    return jnp.dtype(convert_dtype(get_flag("default_dtype")))


def get_default_dtype() -> str:
    """reference: paddle.get_default_dtype (fluid/framework.py)."""
    return default_dtype().name


def set_default_dtype(d: DTypeLike) -> None:
    """reference: paddle.set_default_dtype(d)."""
    from .flags import set_flags
    set_flags({"default_dtype": str(jnp.dtype(convert_dtype(d)))})


def is_floating(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


def finfo(dtype: DTypeLike):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype: DTypeLike):
    return jnp.iinfo(convert_dtype(dtype))
