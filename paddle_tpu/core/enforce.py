"""Typed error/enforce system.

TPU-native equivalent of PADDLE_ENFORCE_* macros with typed error codes
(reference: paddle/fluid/platform/enforce.h, errors.h,
platform/error_codes.proto). Python-level: typed exception classes plus
``enforce`` helpers used throughout the framework for argument/shape checks.
"""

from __future__ import annotations

from typing import Any, NoReturn, Sequence


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: platform/enforce.h EnforceNotMet)."""

    code = "LEGACY"


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


def enforce(cond: Any, msg: str = "Enforce failed",
            exc: type = InvalidArgumentError) -> None:
    if not cond:
        raise exc(msg)


def enforce_eq(a: Any, b: Any, msg: str = "") -> None:
    if a != b:
        raise InvalidArgumentError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_gt(a: Any, b: Any, msg: str = "") -> None:
    if not a > b:
        raise InvalidArgumentError(f"Expected {a!r} > {b!r}. {msg}")


def enforce_ge(a: Any, b: Any, msg: str = "") -> None:
    if not a >= b:
        raise InvalidArgumentError(f"Expected {a!r} >= {b!r}. {msg}")


def enforce_in(a: Any, seq: Sequence[Any], msg: str = "") -> None:
    if a not in seq:
        raise InvalidArgumentError(f"Expected {a!r} in {list(seq)!r}. {msg}")


def enforce_shape_match(shape_a, shape_b, msg: str = "") -> None:
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(
            f"Shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)}. {msg}")


def not_implemented(what: str) -> NoReturn:
    raise UnimplementedError(f"{what} is not implemented yet")
