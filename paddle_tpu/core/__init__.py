"""Core runtime: flags, errors, places, dtypes, RNG, profiling, stats."""

from . import dtype as dtypes
from .dtype import (bfloat16, bool_, complex64, convert_dtype, default_dtype,
                    finfo, float16, float32, float64, iinfo, int16, int32,
                    int64, int8, set_default_dtype, uint8)
from .enforce import (AlreadyExistsError, EnforceNotMet, InvalidArgumentError,
                      NotFoundError, OutOfRangeError, PreconditionNotMetError,
                      UnavailableError, UnimplementedError, enforce,
                      enforce_eq, enforce_ge, enforce_gt, enforce_in,
                      enforce_shape_match)
from .flags import define_flag, get_flag, get_flags, set_flags
from .monitor import GLOBAL_STATS, stat
from .place import (CPUPlace, CUDAPlace, GPUPlace, Place, TPUPlace,
                    device_count, expected_place, get_device,
                    is_compiled_with_tpu, set_device)
from .profiler import (RecordEvent, disable_profiler, enable_profiler,
                       export_chrome_trace, profiler_guard)
from .rng import (Generator, RNGStatesTracker, default_generator,
                  get_rng_state_tracker, next_key, seed)
