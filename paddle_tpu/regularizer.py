"""Weight regularizers (reference: python/paddle/regularizer.py
L1Decay/L2Decay backed by fluid/regularizer.py
L1DecayRegularizer/L2DecayRegularizer).

The optimizer consumes these through its ``weight_decay`` argument: L2Decay
adds ``coeff * param`` to the gradient (or decoupled decay for AdamW-style
optimizers); L1Decay adds ``coeff * sign(param)``.
"""

from __future__ import annotations


class L2Decay:
    """reference: paddle.regularizer.L2Decay — loss += 0.5*coeff*||w||^2,
    i.e. grad += coeff * w."""

    mode = "l2"

    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def grad_term(self, param):
        return self._coeff * param

    def __repr__(self):
        return f"L2Decay(coeff={self._coeff})"


class L1Decay:
    """reference: paddle.regularizer.L1Decay — loss += coeff*||w||_1,
    i.e. grad += coeff * sign(w)."""

    mode = "l1"

    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def grad_term(self, param):
        import jax.numpy as jnp
        return self._coeff * jnp.sign(param)

    def __repr__(self):
        return f"L1Decay(coeff={self._coeff})"
