"""Build configuration paths (reference: python/paddle/sysconfig.py:
get_include / get_lib for compiling custom ops against the install)."""

from __future__ import annotations

import os


def _root() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory with the C headers for custom-op builds (reference:
    paddle.sysconfig.get_include). The custom-op ABI header lives in
    native/ (pt_custom_op.h)."""
    return os.path.join(os.path.dirname(_root()), "native")


def get_lib() -> str:
    """Directory with the native shared library (reference:
    paddle.sysconfig.get_lib)."""
    return os.path.join(os.path.dirname(_root()), "native")
